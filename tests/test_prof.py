"""shadowscope profiling plane: histograms, the interval ring, merging.

The plane's two contracts (docs/observability.md §Profiling):

  * mergeability — a histogram accumulated across fleet lanes, federation
    peers, or a checkpoint-resume boundary is EXACTLY the histogram one
    uninterrupted observer would have built (int64 counts on a fixed
    bucket layout, merge = elementwise add);
  * read-only observation — the recorder never touches simulation state,
    so profiler-on runs keep bit-identical audit chains (gated end to end
    by bench.py --profile-smoke; asserted here on a small islands run).
"""

import json

import pytest

from shadow_tpu.obs import prof as prof_mod
from shadow_tpu.obs.hist import (
    NUM_BINS, SUB_BITS, LogHistogram, bucket_hi, bucket_index, bucket_lo,
)
from shadow_tpu.obs.prof import (
    ProfRecorder, align_series, critical_path, merge_profile_docs,
    validate_profile_doc,
)

from _contracts import assert_current_metrics_schema

NEVER = (1 << 63) - 1


# ---------------------------------------------------------------------------
# bucket layout
# ---------------------------------------------------------------------------


def test_bucket_bounds_cover_every_value():
    import random

    rng = random.Random(7)
    for v in [0, 1, 2, 3, 4, 5, 7, 8, 1023, 1024] + [
        rng.randrange(0, 1 << 60) for _ in range(5000)
    ]:
        i = bucket_index(v)
        lo, hi = bucket_lo(i), bucket_hi(i)
        assert lo <= v, (v, i, lo)
        assert hi is None or v <= hi, (v, i, hi)


def test_bucket_relative_error_bound():
    # log-linear with SUB_BITS sub-buckets per octave: bucket width is
    # at most 2**-SUB_BITS of its lower bound (the HDR error contract)
    for i in range(1 << SUB_BITS, NUM_BINS - 1):
        lo, hi = bucket_lo(i), bucket_hi(i)
        assert (hi - lo + 1) <= max(1, lo >> SUB_BITS), (i, lo, hi)


def test_overflow_bucket_catches_huge_values():
    # every int64 has a bounded bucket; the overflow bin starts at the
    # first value whose index would pass NUM_BINS - 1 (7 * 2**62 with
    # the default layout) and is unbounded above
    h = LogHistogram()
    h.observe(7 << 62)
    h.observe(1 << 70)
    assert h.buckets == {NUM_BINS - 1: 2}
    # percentile clamps to the observed max, never an invented bound
    assert h.percentile(50) == h.max == 1 << 70
    # just below the overflow threshold still lands in a bounded bucket
    assert bucket_index((7 << 62) - 1) < NUM_BINS - 1


def test_empty_histogram_percentile_is_zero():
    h = LogHistogram()
    assert h.percentile(50) == 0
    assert h.percentile(99) == 0
    s = h.summary()
    assert s["count"] == 0 and s["p99"] == 0 and s["mean"] == 0.0


def test_percentiles_nearest_rank():
    h = LogHistogram()
    for v in range(1, 101):  # 1..100, exact buckets only up to 3
        h.observe(v)
    assert h.summary()["count"] == 100
    # p50 falls in the bucket holding rank 50; bounds are quantized but
    # must bracket the true value within the layout's relative error
    p50 = h.percentile(50)
    assert 50 <= p50 <= 63
    assert h.percentile(100) == 100


# ---------------------------------------------------------------------------
# merge algebra
# ---------------------------------------------------------------------------


def _hist_of(values):
    h = LogHistogram()
    for v in values:
        h.observe(v)
    return h


def test_merge_commutative_and_associative():
    a = _hist_of([1, 5, 9000, 1 << 40])
    b = _hist_of([0, 2, 77, 77, 123456789])
    c = _hist_of([3, 3, 3, 1 << 55])

    ab = _hist_of([]) ; ab.merge(a); ab.merge(b)
    ba = _hist_of([]) ; ba.merge(b); ba.merge(a)
    assert ab == ba  # commutative

    ab_c = _hist_of([]); ab_c.merge(ab); ab_c.merge(c)
    bc = _hist_of([]); bc.merge(b); bc.merge(c)
    a_bc = _hist_of([]); a_bc.merge(a); a_bc.merge(bc)
    assert ab_c == a_bc  # associative


def test_merge_equals_uninterrupted_observer():
    vals = [0, 1, 4, 4, 999, 10**7, 1 << 45]
    full = _hist_of(vals)
    split = _hist_of(vals[:3])
    split.merge(_hist_of(vals[3:]))
    assert split == full
    assert split.summary() == full.summary()


def test_doc_roundtrip_and_layout_refusal():
    h = _hist_of([5, 500, 1 << 30])
    assert LogHistogram.from_doc(h.to_doc()) == h
    bad = h.to_doc()
    bad["sub_bits"] = SUB_BITS + 1
    with pytest.raises(ValueError, match="layout mismatch"):
        LogHistogram.from_doc(bad)


# ---------------------------------------------------------------------------
# the interval ring
# ---------------------------------------------------------------------------


def _tick_n(rec, n, *, start=0, step_vt=1000, step_ev=10):
    for k in range(start, start + n):
        rec.tick(vt_ns=(k + 1) * step_vt, events=(k + 1) * step_ev,
                 windows=k + 1)


def test_ring_wraparound_keeps_newest():
    r = ProfRecorder(8)
    _tick_n(r, 20)
    assert r.recorded == 20
    assert r.dropped == 12
    ivs = r.intervals()
    assert len(ivs) == 8
    # oldest-first, and the survivors are the NEWEST 8 intervals
    assert [iv["vt_ns"] for iv in ivs] == [
        (k + 1) * 1000 for k in range(12, 20)
    ]
    assert all(iv["d_vt_ns"] == 1000 for iv in ivs)


def test_ring_capacity_floor():
    with pytest.raises(ValueError, match=">= 8"):
        ProfRecorder(4)


def test_never_frontier_clamps_final_interval():
    r = ProfRecorder(8)
    r.tick(vt_ns=5000, events=10, windows=1)
    r.tick(vt_ns=NEVER, events=20, windows=2)  # drained-pool frontier
    last = r.intervals()[-1]
    assert last["vt_ns"] == 5000 and last["d_vt_ns"] == 0


def test_resume_then_merge_equals_uninterrupted():
    """A run profiled across a checkpoint-resume boundary merges into
    the profile one uninterrupted run would have produced: the resumed
    recorder seeds base_vt_ns from the checkpointed frontier, so the
    first post-resume interval has the width the uninterrupted run saw,
    and the merged histograms are equal by int64 fold."""
    full = ProfRecorder(64)
    _tick_n(full, 10)

    first = ProfRecorder(64)
    _tick_n(first, 6)
    resumed = ProfRecorder(64, base_vt_ns=first.last_vt_ns)
    _tick_n(resumed, 4, start=6)

    merged = merge_profile_docs(
        {"a": first.to_doc(), "b": resumed.to_doc()}
    )
    want = full.to_doc()["hists"]["window_width_ns"]
    got = merged["hists"]["window_width_ns"]
    assert LogHistogram.from_doc(got) == LogHistogram.from_doc(want)
    # and the interleaved series carries every interval exactly once
    assert len(merged["series"]) == 10


def test_profile_doc_validates_and_rejects():
    r = ProfRecorder(8)
    _tick_n(r, 3)
    doc = r.to_doc(meta={"run": "t"})
    validate_profile_doc(doc)
    assert doc["kind"] == prof_mod.PROFILE_DOC_KIND
    assert doc["schema_version"] == prof_mod.PROFILE_SCHEMA_VERSION
    bad = dict(doc)
    bad["schema_version"] = doc["schema_version"] + 1
    with pytest.raises(ValueError, match="schema_version"):
        validate_profile_doc(bad)
    with pytest.raises(ValueError, match="intervals"):
        validate_profile_doc({**doc, "intervals": "nope"})


def test_align_series_orders_across_peers():
    a = ProfRecorder(8)
    _tick_n(a, 2)
    b = ProfRecorder(8)
    _tick_n(b, 2)
    da, db = a.to_doc(), b.to_doc()
    da["t0_unix"], db["t0_unix"] = 100.0, 100.5
    rows = align_series({"p1": da, "p2": db})
    assert len(rows) == 4
    assert [r["t_unix"] for r in rows] == sorted(r["t_unix"] for r in rows)
    assert {r["peer"] for r in rows} == {"p1", "p2"}


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------


def _skewed_doc(shards=3, laggard=1, n=6):
    """Synthetic profile: `laggard` always holds the minimum frontier and
    the other shards rack up blocked deltas."""
    import time

    r = ProfRecorder(64)
    look = [[NEVER] * shards for _ in range(shards)]
    for dst in range(shards):
        for src in range(shards):
            if src != dst:
                look[dst][src] = 5000 + dst
    for k in range(1, n + 1):
        time.sleep(0.001)  # keep d_wall_s above the 1 us rounding floor
        fr = [k * 1000 + 500 * s for s in range(shards)]
        fr[laggard] = k * 1000 - 999  # strictly the minimum
        blocked = [k * 3 if s != laggard else 0 for s in range(shards)]
        r.tick(vt_ns=k * 1000, events=k * 10, windows=k,
               supersteps=k * shards, blocked=sum(blocked),
               frontier_ns=fr, shard_blocked=blocked, lookahead_in=look)
    return r.to_doc()


def test_critical_path_names_laggard_and_link():
    cp = critical_path(_skewed_doc(shards=3, laggard=1))
    assert cp is not None
    assert cp["shards"] == 3
    assert cp["critical_shard"] == 1
    assert cp["wall_frac"] > 0
    link = cp["link"]
    assert link["src"] == 1 and link["dst"] != 1
    # the in-edge bound L[laggard -> victim] travels with the report
    assert link["lookahead_ns"] == 5000 + link["dst"]
    assert 0.0 < cp["blocked_frac"] < 1.0


def test_critical_path_none_without_shard_data():
    r = ProfRecorder(8)
    _tick_n(r, 4)
    assert critical_path(r.to_doc()) is None


# ---------------------------------------------------------------------------
# metrics integration (schema-current prof.* namespace)
# ---------------------------------------------------------------------------


def test_snapshot_prof_emits_namespace_and_validates(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics

    r = ProfRecorder(8)
    r.observe_wall("dispatch_wall_ns", 0.001)
    r.observe_wall("host_drain_wall_ns", 0.002)
    _tick_n(r, 3)
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_prof(r, reg)
    path = str(tmp_path / "m.json")
    doc = reg.dump(path)
    assert_current_metrics_schema(doc)
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    assert doc["counters"]["prof.intervals"] == 3
    assert doc["gauges"]["prof.dispatch_wall_ns_p50"] >= 1_000_000
    # atomic dump landed the final file, no tmp litter
    with open(path) as f:
        assert json.load(f) == doc
    assert list(tmp_path.iterdir()) == [tmp_path / "m.json"]


def _islands_cfg(shards=2, per=2, stop=6, seed=11):
    """Tiny async-islands config (the test_async_sync.py shape): one
    vertex per host, distinct cross-shard latencies for lookahead."""
    import numpy as np

    rng = np.random.RandomState(7)
    n = shards * per
    lines = ["graph ["]
    for v in range(n):
        lines.append(f"  node [ id {v} ]")
    for a in range(n):
        for b in range(a, n):
            lo, hi = ((700000, 900000) if a // per != b // per
                      else (30000, 250000))
            lines.append(
                f'  edge [ source {a} target {b} latency '
                f'"{int(rng.randint(lo, hi))} us" ]'
            )
    lines.append("]")
    hosts = {
        f"h{v:02d}": {
            "quantity": 1, "network_node_id": v, "app_model": "phold",
            "app_options": {"msgload": 1, "runtime": stop - 1,
                            "local_span": 1},
        }
        for v in range(n)
    }
    return {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "gml", "inline": "\n".join(lines)}},
        "experimental": {
            "event_capacity": 1024, "events_per_host_per_window": 8,
            "outbox_slots": 8, "inbox_slots": 4,
            "num_shards": shards, "exchange_slots": 16,
        },
        "hosts": hosts,
    }


def test_profiled_run_keeps_chain_and_records():
    """The read-only contract on a real (tiny) islands run: attaching a
    profiling session changes NO simulation outcome, and the recorder
    sees handoff boundaries with a monotone committed frontier."""
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.sim import build_simulation

    plain = build_simulation(_islands_cfg())
    assert plain._async is True
    plain.run(windows_per_dispatch=64)

    prof = ProfRecorder(16)
    profiled = build_simulation(_islands_cfg())
    profiled.obs_session = obs_metrics.ObsSession(prof=prof)
    profiled.run(windows_per_dispatch=64)

    assert profiled.audit_chain() == plain.audit_chain()
    assert (profiled.counters()["events_committed"]
            == plain.counters()["events_committed"])
    assert prof.recorded > 0
    vts = [iv["vt_ns"] for iv in prof.intervals()]
    assert vts == sorted(vts)
    assert vts[-1] < NEVER  # the drained-pool NEVER frontier clamped
    validate_profile_doc(prof.to_doc())


def test_config_profiler_knobs():
    from shadow_tpu.core.config import ConfigError, load_config

    def cfg(**exp):
        return {
            "general": {"stop_time": 1},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "experimental": exp,
            "hosts": {"h": {"quantity": 1}},
        }

    c = load_config(cfg(profiler=True, profiler_ring=64))
    assert c.experimental.profiler is True
    assert c.experimental.profiler_ring == 64
    assert load_config(cfg()).experimental.profiler is False
    with pytest.raises(ConfigError, match="profiler_ring"):
        load_config(cfg(profiler_ring=4))


# ---------------------------------------------------------------------------
# tools (loaded the way tpu_watch invokes them)
# ---------------------------------------------------------------------------


def _load_tool(name):
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        name, pathlib.Path(__file__).parent.parent / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _metrics_doc(tmp_path, fname, counters=None, gauges=None, meta=None):
    from shadow_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.MetricsRegistry()
    for k, v in (counters or {}).items():
        reg.counter_set(k, v)
    for k, v in (gauges or {}).items():
        reg.gauge_set(k, v)
    path = tmp_path / fname
    reg.dump(str(path), meta=meta)
    return str(path)


def test_perf_compare_directions_and_rc(tmp_path):
    pc = _load_tool("perf_compare")

    base = {"counters": {"engine.events_committed": 100},
            "gauges": {"prof.dispatch_wall_ns_p50": 1000,
                       "free.key": 7},
            "meta": {"wall_s": 10.0}}
    cand_ok = {"counters": {"engine.events_committed": 100},
               "gauges": {"prof.dispatch_wall_ns_p50": 1400,  # +40% < 50%
                          "free.key": 9},
               "meta": {"wall_s": 11.0}}
    r = pc.compare_docs(base, cand_ok)
    assert r["regressions"] == []
    assert {row["key"] for row in r["drift"]} == {
        "prof.dispatch_wall_ns_p50", "free.key", "meta.wall_s"
    }

    cand_bad = {"counters": {"engine.events_committed": 99},  # eq breach
                "gauges": {"prof.dispatch_wall_ns_p50": 1600},  # +60%
                "meta": {"wall_s": 20.0}}  # +100% > 50%
    r = pc.compare_docs(base, cand_bad)
    assert {row["key"] for row in r["regressions"]} == {
        "engine.events_committed", "prof.dispatch_wall_ns_p50",
        "meta.wall_s",
    }

    # end to end: identical docs exit 0, a determinism breach exits 1,
    # and --json emits ONE parseable line (tpu_watch scrapes per-line)
    a = _metrics_doc(tmp_path, "a.json",
                     counters={"engine.events_committed": 5})
    b = _metrics_doc(tmp_path, "b.json",
                     counters={"engine.events_committed": 5})
    c = _metrics_doc(tmp_path, "c.json",
                     counters={"engine.events_committed": 6})
    assert pc.main([a, b]) == 0
    assert pc.main([a, c]) == 1
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert pc.main([a, c, "--json"]) == 1
    out = buf.getvalue().strip()
    assert "\n" not in out
    parsed = json.loads(out)
    assert parsed["regressions"][0]["key"] == "engine.events_committed"


def test_perf_compare_skips_failed_and_cross_schema(tmp_path):
    pc = _load_tool("perf_compare")

    good = _metrics_doc(tmp_path, "g.json",
                        counters={"engine.events_committed": 5})
    # ok:false — the producing gate already failed; not a perf signal
    failed = _metrics_doc(tmp_path, "f.json",
                          counters={"engine.events_committed": 1},
                          meta={"ok": False})
    assert pc.main([good, failed]) == 0

    # stale schema artifact: numbers are not comparable, skip not gate
    stale = json.loads((tmp_path / "g.json").read_text())
    stale["schema_version"] -= 1
    (tmp_path / "stale.json").write_text(json.dumps(stale))
    assert pc.main([str(tmp_path / "stale.json"), good]) == 0

    # not a metrics doc at all
    (tmp_path / "junk.json").write_text('{"kind": "other"}')
    assert pc.main([str(tmp_path / "junk.json"), good]) == 0
    (tmp_path / "broken.json").write_text("{not json")
    assert pc.main([str(tmp_path / "broken.json"), good]) == 2


def test_trace_merge_aligns_peer_clocks(tmp_path):
    tm = _load_tool("trace_merge")

    def trace(t0, names):
        return {
            "metadata": {"format": "chrome-trace-events", "t0_unix": t0},
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
                 "args": {"name": "shadow_tpu"}},
            ] + [
                {"name": n, "ph": "X", "pid": 0, "tid": 0,
                 "ts": 100.0 * i, "dur": 50.0}
                for i, n in enumerate(names)
            ],
        }

    docs = {"a": trace(100.0, ["dispatch", "host_drain"]),
            "b": trace(101.5, ["dispatch"])}
    fused = tm.merge_traces(docs)
    peers = fused["metadata"]["peers"]
    assert peers["a"]["pid"] == 1 and peers["b"]["pid"] == 2
    assert peers["a"]["offset_us"] == 0.0
    assert peers["b"]["offset_us"] == 1.5e6  # +1.5 s behind the anchor
    b_spans = [e for e in fused["traceEvents"]
               if e.get("ph") == "X" and e["pid"] == 2]
    assert b_spans[0]["ts"] == 1.5e6  # shifted onto the shared clock
    # original process_name rows replaced by peer-named ones
    names = {e["args"]["name"] for e in fused["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert names == {"a", "b"}

    # end to end through the CLI, stem-named inputs
    pa, pb = tmp_path / "pa.trace.json", tmp_path / "pb.trace.json"
    pa.write_text(json.dumps(docs["a"]))
    pb.write_text(json.dumps(docs["b"]))
    out = tmp_path / "fused.json"
    assert tm.main([str(pa), str(pb), "-o", str(out)]) == 0
    assert json.loads(out.read_text())["metadata"]["merged"] is True
    (tmp_path / "bad.json").write_text('{"no": "traceEvents"}')
    assert tm.main([str(tmp_path / "bad.json"), "-o", str(out)]) == 2


def test_trace_summary_percentiles():
    ts = _load_tool("trace_summary")

    doc = {"traceEvents": [
        {"name": "dispatch", "ph": "X", "ts": 0, "dur": d}
        for d in (1000.0, 2000.0, 3000.0, 4000.0)  # us
    ] + [
        {"name": "host_drain", "ph": "X", "ts": 0, "dur": 10000.0},
        {"name": "meta", "ph": "M"},
    ]}
    rows = ts.percentiles(doc)
    assert [r["name"] for r in rows] == ["host_drain", "dispatch"]
    d = rows[1]
    assert d["count"] == 4
    assert d["p50_ms"] == 2.0  # nearest rank: 2nd of 4
    assert d["p99_ms"] == 4.0
    assert ts.percentiles({"traceEvents": []}) == []


def test_shadowctl_render_top():
    ctl = _load_tool("shadowctl")

    assert ctl._fmt_ns(512) == "512ns"
    assert ctl._fmt_ns(1_500) == "1.5us"
    assert ctl._fmt_ns(2_500_000) == "2.5ms"
    assert ctl._fmt_ns(3_000_000_000) == "3.00s"

    frame = ctl.render_top(_skewed_doc())
    assert "shadowscope top" in frame
    assert "window_width_ns" in frame
    assert "critical" in frame

    # the router's merged document renders with the peer header
    a, b = ProfRecorder(8), ProfRecorder(8)
    _tick_n(a, 2)
    _tick_n(b, 3)
    merged = merge_profile_docs({"pa": a.to_doc(), "pb": b.to_doc()})
    frame = ctl.render_top(merged)
    assert "2 peer(s)" in frame
    assert "pa(2iv)" in frame and "pb(3iv)" in frame
