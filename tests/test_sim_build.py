import jax

from shadow_tpu.core import simtime
from shadow_tpu.net.apps import PholdApp
from shadow_tpu.sim import build_simulation

PHOLD_YAML = """
general:
  stop_time: 4
  seed: 7
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 1024
  events_per_host_per_window: 8
hosts:
  peer:
    quantity: 8
    app_model: phold
    app_options: {msgload: 1, runtime: 2}
"""


def test_build_and_run_from_yaml():
    sim = build_simulation(PHOLD_YAML)
    assert sim.num_hosts == 8
    assert sim.runahead == 50 * simtime.NS_PER_MS
    assert sim.dns.resolve_name("peer1") is not None
    sim.run()
    c = sim.counters()
    assert c["events_committed"] > 0
    assert c["pool_overflow_dropped"] == 0
    sub = jax.device_get(sim.state.subs[PholdApp.SUB])
    # message population is conserved until runtime ends: every host received
    # at least its own seed
    assert sum(sub["received"]) >= 8


def test_graft_entry_single():
    import __graft_entry__ as g

    fn, args = g.entry()
    state, min_next = fn(*args)
    assert int(min_next) > 0


def test_graft_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_device_plane_deterministic():
    """determinism1 analog for the device plane: two identical runs produce
    bit-identical final state (SURVEY §4 flagship property)."""
    import jax
    import numpy as np

    def run_once():
        sim = build_simulation(PHOLD_YAML)
        sim.run()
        return jax.device_get((sim.state.pool, sim.state.host,
                               sim.state.counters, sim.state.subs))

    a, b = run_once(), run_once()
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        assert np.array_equal(np.asarray(la), np.asarray(lb))
