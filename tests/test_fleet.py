"""Scenario fleet (shadow_tpu/fleet): batched multi-experiment execution.

The load-bearing guarantee is BIT-PARITY: every job of a batched fleet —
committed events, full engine counters, app sub-state, virtual-time
frontier — must equal the same scenario run solo, across the engine
matrix (conservative AND optimistic, global AND islands), through ragged
completion and lane swaps, with ONE window-kernel compile for the whole
sweep (the trace-count metric). Plus the scheduler plane: sweep
expansion/validation, job-scoped fault quarantine, wall deadlines, and
checkpoint/resume of a partially-finished fleet.
"""

import jax
import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.fleet import (
    FleetError,
    JobSpec,
    SweepError,
    build_fleet,
    expand_sweep,
    resume_fleet,
    save_fleet,
)
from shadow_tpu.obs import counters as obs_counters
from shadow_tpu.sim import build_simulation

GML = """\
graph [
  node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""


def _cfg(seed, stop, shards=0, faults=None, hosts=8):
    exp = {
        "event_capacity": 1024,
        "events_per_host_per_window": 8,
        "outbox_slots": 8,
        "inbox_slots": 4,
    }
    if shards:
        exp.update({"num_shards": shards, "exchange_slots": 16})
    d = {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "gml", "inline": GML}},
        "experimental": exp,
        "hosts": {
            "peer": {
                "quantity": hosts,
                "app_model": "phold",
                "app_options": {
                    "msgload": 2, "runtime": 2, "start_time": "100 ms",
                },
            }
        },
    }
    if faults:
        d["faults"] = faults
    return d


# 8 mixed-length scenarios: four distinct stop times, distinct seeds —
# ragged completion is structural, not incidental
_STOPS = ["700 ms", "1.2 s", "1.8 s", "1.5 s"] * 2


def _jobs(shards=0, n=8):
    return [
        JobSpec(f"job{i}", _cfg(100 + i, _STOPS[i], shards=shards))
        for i in range(n)
    ]


def _solo_fingerprint(cfg, drop=()):
    sim = build_simulation(cfg)
    sim.run()
    c = sim.counters()
    for k in drop:
        c.pop(k)
    subs = jax.device_get(sim.state.subs)
    snap = obs_counters.snapshot(sim.state)
    frontier = int(snap["host_last_t"].max()) if snap else -1
    return c, subs, frontier


def _assert_job_matches_solo(rec, cfg, drop=()):
    c, subs, frontier = _solo_fingerprint(cfg, drop)
    fc = dict(rec.counters)
    for k in drop:
        fc.pop(k)
    assert fc == c, (rec.name, fc, c)
    assert rec.frontier_ns == frontier, rec.name
    for key in subs:
        for leaf_a, leaf_b in zip(
            jax.tree.leaves(subs[key]), jax.tree.leaves(rec.subs[key])
        ):
            assert np.array_equal(
                np.asarray(leaf_a),
                np.asarray(leaf_b).reshape(np.asarray(leaf_a).shape),
            ), (rec.name, key)


# ---------------------------------------------------------------------------
# sweep expansion / validation (host-only, no device work)
# ---------------------------------------------------------------------------


def _sweep_doc(matrix):
    return {"sweep": {"name": "t", "matrix": matrix}, **_cfg(1, "1 s")}


def test_sweep_matrix_expansion():
    jobs = expand_sweep(_sweep_doc({
        "general.seed": [1, 2, 3],
        "general.stop_time": ["700 ms", "1.2 s"],
    }))
    assert len(jobs) == 6
    assert len({j.name for j in jobs}) == 6
    # declaration order: first key slowest
    assert [j.config["general"]["seed"] for j in jobs] == [1, 1, 2, 2, 3, 3]
    assert jobs[1].config["general"]["stop_time"] == "1.2 s"


def test_sweep_rejects_kernel_shaping_axes():
    # msgload compiles into the PHOLD handlers: one kernel cannot serve it
    with pytest.raises(SweepError, match="kernel-shaping"):
        expand_sweep(_sweep_doc({
            "hosts.peer.app_options.msgload": [1, 2],
        }))


def test_sweep_rejects_bad_specs():
    with pytest.raises(SweepError, match="unknown"):
        expand_sweep({"sweep": {"matrix": {}, "bogus": 1}, **_cfg(1, "1 s")})
    with pytest.raises(SweepError, match="not present"):
        expand_sweep(_sweep_doc({"general.nonsense": [1]}))
    with pytest.raises(SweepError, match="zero jobs"):
        expand_sweep({"sweep": {"matrix": {}}, **_cfg(1, "1 s")})
    # a matrix value the config parser rejects fails with the job named
    with pytest.raises(SweepError, match="job .*seed"):
        expand_sweep(_sweep_doc({"general.seed": ["not-a-seed"]}))
    # fleet jobs are device-plane only
    doc = _sweep_doc({"general.seed": [1]})
    doc["hosts"]["peer"] = {
        "quantity": 1, "processes": [{"path": "/bin/true"}],
    }
    del doc["hosts"]["peer"]["quantity"]
    with pytest.raises(SweepError, match="device plane"):
        expand_sweep(doc)


def test_fleet_rejects_incompatible_jobs():
    jobs = [
        JobSpec("a", _cfg(1, "1 s", hosts=8)),
        JobSpec("b", _cfg(2, "1 s", hosts=16)),
    ]
    with pytest.raises((SweepError, FleetError)):
        build_fleet(jobs)


def test_requeue_reenters_queue_in_submission_order():
    """Requeued jobs (backend drain / shrunk-fleet resume) must re-enter
    the pending queue at their ORIGINAL submission position, never at the
    tail behind later submissions — including jobs submitted dynamically
    (scheduler.submit, the serve-daemon path) after the requeued job first
    ran."""
    from shadow_tpu.fleet.scheduler import FleetScheduler

    specs = [JobSpec(name=n, config={}) for n in ("a", "b", "c", "d")]
    s = FleetScheduler(specs, lanes=2)
    s.admit(0, s.peek())  # a
    s.admit(1, s.peek())  # b
    # a finishes; c enters its lane — the cursor is now past b
    s.release(0, "done")
    s.admit(0, s.peek())  # c
    # a later tenant submits e while b and c are in flight
    s.submit(JobSpec(name="e", config={}))
    # backend drain returns BOTH running jobs to the queue (lane order,
    # which is NOT submission order: c rides lane 0, b rides lane 1)
    s.requeue(0, "backend drain")  # c
    s.requeue(1, "backend drain")  # b
    assert [r.name for r in s.pending()] == ["b", "c", "d", "e"]
    # admission drains the queue in exactly that order
    order = []
    for lane in (0, 1, 0, 1):
        rec = s.peek()
        s.admit(lane, rec)
        order.append(rec.name)
        s.release(lane, "done")
    assert order == ["b", "c", "d", "e"]
    assert s.jobs_requeued == 2
    # duplicate dynamic submissions are refused
    with pytest.raises(ValueError, match="duplicate"):
        s.submit(JobSpec(name="e", config={}))


# ---------------------------------------------------------------------------
# bit-parity: the acceptance matrix
# ---------------------------------------------------------------------------


def test_fleet_conservative_parity_ragged_and_swaps():
    """THE acceptance gate: an 8-job mixed-length sweep on 4 lanes —
    ragged completion AND four lane swaps — with every job bit-identical
    to its solo run (full counters, including schedule metrics: the
    per-lane window sequence is exactly the solo driver's) and exactly
    ONE window-kernel compile for the whole sweep."""
    jobs = _jobs()
    fleet = build_fleet(jobs, lanes=4, keep_final_subs=True)
    fleet.run()
    stats = fleet.fleet_stats()
    assert stats["jobs_done"] == 8
    assert stats["lane_swaps"] == 4  # 8 jobs through 4 lanes
    assert stats["kernel_traces"] == 1  # compile once, reuse the lane
    for rec, job in zip(fleet.records(), jobs):
        assert rec.status == "done"
        assert rec.events_committed > 0
        _assert_job_matches_solo(rec, job.config)


def test_fleet_islands_conservative_parity():
    """The fleet axis composes with the islands engine: vmap-of-jobs
    outside, shards inside. Per-job results must still equal the solo
    islands runs bit-for-bit, one compile total."""
    jobs = _jobs(shards=2, n=3)
    fleet = build_fleet(jobs, lanes=2, keep_final_subs=True)
    fleet.run()
    assert fleet.fleet_stats()["kernel_traces"] == 1
    assert fleet.fleet_stats()["lane_swaps"] == 1
    for rec, job in zip(fleet.records(), jobs):
        _assert_job_matches_solo(rec, job.config)


# schedule metrics that optimistic runs legitimately take different paths
# on (mirrors tests/test_optimistic.py's fingerprint)
_OPT_DROP = (
    "micro_steps", "outbox_stall_deferred", "exchange_sent",
    "exchange_deferred",
)


def test_fleet_optimistic_parity():
    """Per-lane speculative windows (vmapped fused attempts) must
    reproduce the solo conservative results for every job, through a
    lane swap."""
    jobs = _jobs(n=3)
    fleet = build_fleet(jobs, lanes=2, keep_final_subs=True)
    rounds, rollbacks = fleet.run_optimistic(window_factor=8)
    assert rounds > 0
    assert fleet.fleet_stats()["jobs_done"] == 3
    for rec, job in zip(fleet.records(), jobs):
        _assert_job_matches_solo(rec, job.config, drop=_OPT_DROP)


def test_fleet_islands_optimistic_parity():
    """Optimistic × islands × fleet: host-driven sub-step rounds over
    vmap-of-jobs(vmap-of-shards), with per-lane exchange-backpressure
    floors. Results must equal the solo conservative runs."""
    jobs = _jobs(shards=2, n=2)
    fleet = build_fleet(jobs, keep_final_subs=True)
    fleet.run_optimistic(window_factor=8)
    assert fleet.fleet_stats()["jobs_done"] == 2
    for rec, job in zip(fleet.records(), jobs):
        _assert_job_matches_solo(rec, job.config, drop=_OPT_DROP)


# ---------------------------------------------------------------------------
# job-scoped fault quarantine
# ---------------------------------------------------------------------------


def test_kill_host_quarantines_exactly_one_lane():
    """An injected kill_host in ONE job's fault plan drains that job's
    lane only: the faulted job must bit-match a SOLO run with the same
    fault plan (injection timing included), and the clean neighbor must
    bit-match a solo no-fault run."""
    faults = {"inject": [{"at": "500 ms", "op": "kill_host", "host": 3}]}
    jobs = [
        JobSpec("clean", _cfg(50, "1.2 s")),
        JobSpec("faulty", _cfg(50, "1.2 s", faults=faults)),
    ]
    fleet = build_fleet(jobs, keep_final_subs=True)
    fleet.run()
    clean, faulty = fleet.records()
    assert clean.faults == {}
    assert faulty.faults["hosts_quarantined"] == 1
    assert faulty.faults["injections_fired"] == 1
    assert faulty.faults["events_drained"] > 0
    assert faulty.events_committed < clean.events_committed

    # clean lane: untouched by the neighbor's fault
    _assert_job_matches_solo(clean, jobs[0].config)

    # faulty lane: identical to the solo faulted run
    from shadow_tpu.core.config import load_config

    solo = build_simulation(jobs[1].config)
    solo.attach_faults(load_config(jobs[1].config).faults.load_faults())
    solo.run()
    assert faulty.counters == solo.counters()
    assert (
        faulty.faults["events_drained"]
        == solo.fault_counters["events_drained"]
    )


def test_fleet_floor_width_violation_refuses_commit():
    """The fleet driver carries the same floor-commit guard as the solo
    engines (ADVICE r5 #1): a forged violation inside a floor-width
    window must raise, naming the lane, instead of committing."""
    import jax.numpy as jnp

    fleet = build_fleet(_jobs(n=2))

    def forged(state, params, ws, we):
        return state, we, ws  # "complete" but violated at the window start

    fleet._attempt = forged  # _ensure_attempt keeps a non-None kernel
    with pytest.raises(RuntimeError, match="refusing to commit"):
        fleet.run_optimistic(window_factor=1)


def test_fleet_rejects_proc_fault_ops():
    faults = {"inject": [{"at": "1 s", "op": "kill_proc", "proc": "x.0"}]}
    with pytest.raises(SweepError, match="kill_host"):
        build_fleet([JobSpec("a", _cfg(1, "1 s", faults=faults))])


# ---------------------------------------------------------------------------
# scheduler plane: deadlines, checkpoint/resume, metrics
# ---------------------------------------------------------------------------


def test_wall_deadline_times_out_one_job():
    jobs = [
        JobSpec("ok", _cfg(70, "1.2 s")),
        JobSpec("slow", _cfg(71, "1.2 s"), deadline_s=1e-9),
    ]
    fleet = build_fleet(jobs, keep_final_subs=True)
    fleet.run(windows_per_dispatch=2)
    ok, slow = fleet.records()
    assert slow.status == "timeout"
    assert "deadline" in slow.reason
    assert ok.status == "done"
    _assert_job_matches_solo(ok, jobs[0].config)


def test_fleet_checkpoint_resume(tmp_path):
    """A fleet interrupted mid-sweep resumes from its per-job slices +
    manifest and finishes with results identical to an uninterrupted
    run: completed jobs keep their recorded results, running lanes
    restore bit-exactly, queued jobs re-queue."""
    jobs = _jobs(n=4)
    full = build_fleet(jobs, lanes=2)
    full.run()
    want = {r.name: r.summary() for r in full.records()}

    part = build_fleet(jobs, lanes=2)
    part.run(windows_per_dispatch=4, max_dispatches=3)
    statuses = {r.status for r in part.records()}
    assert "queued" in statuses or "running" in statuses  # truly partial
    d = tmp_path / "fleet-ckpt"
    save_fleet(part, str(d))
    assert (d / "manifest.json").exists()

    res = resume_fleet(str(d))
    res.run()
    for name, w in want.items():
        g = next(r for r in res.records() if r.name == name).summary()
        assert g["counters"] == w["counters"], name
        assert g["events_committed"] == w["events_committed"], name
        assert g["frontier_ns"] == w["frontier_ns"], name


def test_metrics_schema_v5_fleet_section():
    from shadow_tpu.obs import metrics as obs_metrics

    jobs = _jobs(n=2)
    fleet = build_fleet(jobs)
    fleet.run()
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_fleet(fleet, reg)
    doc = reg.to_doc()
    obs_metrics.validate_metrics_doc(doc)
    assert_current_metrics_schema(doc)
    rows = doc["fleet"]["jobs"]
    assert len(rows) == 2
    assert all(r["status"] == "done" for r in rows)
    # schema v5: every harvested row carries its determinism-audit chain
    assert all(isinstance(r["audit"].get("chain"), int) for r in rows)
    assert doc["counters"]["fleet.kernel_traces"] == 1
    # the validator actually gates the audit sub-object...
    import copy as _copy

    bad = _copy.deepcopy(doc)
    bad["fleet"]["jobs"][0]["audit"] = {"bogus": 1}
    with pytest.raises(ValueError, match="audit"):
        obs_metrics.validate_metrics_doc(bad)
    # ...and still gates the base fleet rows
    rows[0].pop("frontier_ns")
    with pytest.raises(ValueError, match="fleet.jobs"):
        obs_metrics.validate_metrics_doc(doc)
