"""Checkpoint/resume: run→snapshot→resume must be bit-exact vs an
uninterrupted run (a capability the reference lacks — SURVEY.md §5.4),
and every failure path — truncation, corruption, structure mismatch —
must surface as a clean CheckpointError, never a zipfile/KeyError
internal, with the retention ring falling back past bad entries."""

import io
import json
import shutil

import jax
import numpy as np
import pytest

from shadow_tpu.core import checkpoint as ck
from shadow_tpu.core import simtime
from shadow_tpu.core.checkpoint import CheckpointError, load_meta
from shadow_tpu.sim import build_simulation

pytestmark = pytest.mark.quick


YAML = """
general:
  stop_time: 4
  seed: 13
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 1024
  events_per_host_per_window: 8
hosts:
  peer:
    quantity: 8
    app_model: phold
    app_options: {msgload: 1, runtime: 3}
"""


def _states_equal(a, b) -> bool:
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(fa, fb)
    )


def test_resume_bit_exact(tmp_path):
    ckpt = str(tmp_path / "sim.ckpt.npz")

    # uninterrupted run
    ref = build_simulation(YAML)
    ref.run()

    # run half, checkpoint, resume in a FRESH Simulation, finish
    half = build_simulation(YAML)
    half.run(until=2 * simtime.NS_PER_SEC)
    half.save_checkpoint(ckpt)

    meta = load_meta(ckpt)
    assert meta["num_hosts"] == 8

    resumed = build_simulation(YAML)
    resumed.load_checkpoint(ckpt)
    resumed.run()

    assert _states_equal(ref.state, resumed.state)
    assert ref.counters() == resumed.counters()


def test_restore_rejects_other_config(tmp_path):
    ckpt = str(tmp_path / "sim.ckpt.npz")
    sim = build_simulation(YAML)
    sim.save_checkpoint(ckpt)

    other = build_simulation(YAML.replace("quantity: 8", "quantity: 4"))
    with pytest.raises(CheckpointError, match="hosts"):
        other.load_checkpoint(ckpt)


# ---------------------------------------------------------------------------
# failure paths: every corruption class must raise CheckpointError cleanly
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def good_ckpt(tmp_path_factory):
    """One sim + one valid checkpoint shared by the failure-path tests
    (they only ever copy/tamper the file, never mutate the good one)."""
    d = tmp_path_factory.mktemp("ckpt")
    sim = build_simulation(YAML)
    sim.run(until=1 * simtime.NS_PER_SEC)
    path = str(d / "good.npz")
    sim.save_checkpoint(path)
    return sim, path


def _rewrite(src: str, dst: str, mutate) -> None:
    """Load every entry of a checkpoint, apply `mutate(arrays, meta)`,
    re-sign with a VALID digest, and write `dst` — forging structurally
    wrong archives whose corruption only semantic validation can catch."""
    with np.load(src) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode())
    mutate(arrays, meta)
    meta["leaves"] = sorted(arrays)
    meta["digest"] = ck._digest(arrays)
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8
    )
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    with open(dst, "wb") as f:
        f.write(buf.getvalue())


def test_truncated_archive_clean_error(good_ckpt, tmp_path):
    _, good = good_ckpt
    bad = str(tmp_path / "trunc.npz")
    shutil.copy(good, bad)
    size = len(open(bad, "rb").read())
    with open(bad, "r+b") as f:
        f.truncate(size // 2)
    with pytest.raises(CheckpointError):
        ck.verify(bad)


def test_flipped_bytes_fail_digest(good_ckpt, tmp_path):
    sim, good = good_ckpt
    bad = str(tmp_path / "flip.npz")
    shutil.copy(good, bad)
    size = len(open(bad, "rb").read())
    off = size // 2
    with open(bad, "r+b") as f:
        f.seek(off)
        span = f.read(64)
        f.seek(off)
        f.write(bytes(x ^ 0xFF for x in span))
    with pytest.raises(CheckpointError):
        ck.verify(bad)
    with pytest.raises(CheckpointError):
        ck.restore(sim, bad)


def test_corrupt_meta_clean_error(good_ckpt, tmp_path):
    _, good = good_ckpt
    # __meta__ present but not JSON
    bad = str(tmp_path / "badmeta.npz")
    with np.load(good) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    arrays["__meta__"] = np.frombuffer(b"\x00garbage", dtype=np.uint8)
    np.savez_compressed(bad, **arrays)
    with pytest.raises(CheckpointError, match="__meta__"):
        load_meta(bad)
    # __meta__ missing entirely: CheckpointError, not KeyError
    bad2 = str(tmp_path / "nometa.npz")
    np.savez_compressed(bad2, **{k: v for k, v in arrays.items()
                                 if k != "__meta__"})
    with pytest.raises(CheckpointError, match="__meta__"):
        load_meta(bad2)
    # not a zip at all
    bad3 = str(tmp_path / "notzip.npz")
    with open(bad3, "wb") as f:
        f.write(b"this is not an archive")
    with pytest.raises(CheckpointError):
        load_meta(bad3)


def test_version_mismatch_clean_error(good_ckpt, tmp_path):
    sim, good = good_ckpt
    bad = str(tmp_path / "oldver.npz")
    _rewrite(good, bad, lambda arrays, meta: meta.update(version=1))
    with pytest.raises(CheckpointError, match="version"):
        ck.restore(sim, bad)


def test_leaf_shape_mismatch_clean_error(good_ckpt, tmp_path):
    sim, good = good_ckpt
    bad = str(tmp_path / "shape.npz")

    def shrink_one(arrays, meta):
        key = next(k for k in sorted(arrays) if arrays[k].ndim >= 1
                   and arrays[k].shape[0] > 1)
        arrays[key] = arrays[key][:-1]

    _rewrite(good, bad, shrink_one)
    with pytest.raises(CheckpointError, match="leaf"):
        ck.restore(sim, bad)


def test_leaf_set_mismatch_clean_error(good_ckpt, tmp_path):
    sim, good = good_ckpt
    bad = str(tmp_path / "missing.npz")
    _rewrite(good, bad,
             lambda arrays, meta: arrays.pop(sorted(arrays)[0]))
    with pytest.raises(CheckpointError, match="structure mismatch"):
        ck.restore(sim, bad)


def test_ring_fallback_restores_previous_good(tmp_path):
    """Retention ring: resume falls back past a corrupt newest checkpoint
    to the previous good one, and the resumed run still finishes with the
    uninterrupted run's exact totals."""
    ref = build_simulation(YAML)
    ref.run()

    d = str(tmp_path / "ring")
    sim = build_simulation(YAML)
    sim.run(until=1 * simtime.NS_PER_SEC)
    ck.save_ring(sim, d, 0, 1 * simtime.NS_PER_SEC, retain=3)
    sim.run(until=2 * simtime.NS_PER_SEC)
    ck.save_ring(sim, d, 1, 2 * simtime.NS_PER_SEC, retain=3)

    # corrupt the NEWEST entry (XOR a span mid-file)
    newest = ck.ring_entries(d)[-1][2]
    size = len(open(newest, "rb").read())
    with open(newest, "r+b") as f:
        f.seek(size // 2)
        span = f.read(64)
        f.seek(size // 2)
        f.write(bytes(x ^ 0xFF for x in span))

    resumed = build_simulation(YAML)
    info = resumed.resume_from(d)
    assert info["fallbacks"] == 1
    assert info["path"].endswith(f"ckpt-000000-{1 * simtime.NS_PER_SEC}.npz")
    assert resumed.fault_counters["resume_fallbacks"] == 1
    resumed.run()
    assert resumed.counters() == ref.counters()
    assert _states_equal(ref.state, resumed.state)


def test_ring_fallback_past_truncated_and_empty_entries(tmp_path):
    """A zero-length ring entry (open() crashed before any write reached
    disk) and a mid-write-truncated one must BOTH collapse to the clean
    CheckpointError fallback path — never a raw zipfile/numpy traceback —
    and resume still lands on the older intact entry bit-exactly."""
    ref = build_simulation(YAML)
    ref.run()

    d = str(tmp_path / "ring")
    sim = build_simulation(YAML)
    sim.run(until=1 * simtime.NS_PER_SEC)
    ck.save_ring(sim, d, 0, 1 * simtime.NS_PER_SEC, retain=4)
    sim.run(until=2 * simtime.NS_PER_SEC)
    ck.save_ring(sim, d, 1, 2 * simtime.NS_PER_SEC, retain=4)
    sim.run(until=3 * simtime.NS_PER_SEC)
    ck.save_ring(sim, d, 2, 3 * simtime.NS_PER_SEC, retain=4)

    entries = ck.ring_entries(d)
    # newest entry: zero-length (truncate-to-nothing)
    open(entries[2][2], "w").close()
    # second-newest: torn mid-write (keep a prefix only)
    blob = open(entries[1][2], "rb").read()
    with open(entries[1][2], "wb") as f:
        f.write(blob[: len(blob) // 3])

    # both bad entries individually raise the clean error type
    for _, _, path in (entries[2], entries[1]):
        with pytest.raises(CheckpointError):
            ck.verify(path)
        with pytest.raises(CheckpointError):
            load_meta(path)

    resumed = build_simulation(YAML)
    info = resumed.resume_from(d)
    assert info["fallbacks"] == 2
    assert info["path"] == entries[0][2]
    resumed.run()
    assert resumed.counters() == ref.counters()
    assert _states_equal(ref.state, resumed.state)


def test_non_npz_checkpoint_clean_error(good_ckpt, tmp_path):
    """A ckpt file overwritten with bare .npy bytes (not an archive) must
    raise CheckpointError, not an attribute/index error on the NpzFile
    duck type."""
    _, good = good_ckpt
    bad = str(tmp_path / "bare.npz")
    np.save(open(bad, "wb"), np.arange(16))
    with pytest.raises(CheckpointError, match="npz"):
        load_meta(bad)
    with pytest.raises(CheckpointError):
        ck.verify(bad)


def test_save_is_atomic_no_tmp_left(good_ckpt, tmp_path):
    sim, _ = good_ckpt
    path = str(tmp_path / "atomic.npz")
    ck.save(sim, path)
    assert ck.verify(path)["num_hosts"] == 8
    # no temp droppings next to the checkpoint
    leftovers = [p.name for p in tmp_path.iterdir() if ".tmp." in p.name]
    assert leftovers == []
