"""Checkpoint/resume: run→snapshot→resume must be bit-exact vs an
uninterrupted run (a capability the reference lacks — SURVEY.md §5.4)."""

import jax
import numpy as np
import pytest

from shadow_tpu.core import simtime
from shadow_tpu.core.checkpoint import CheckpointError, load_meta
from shadow_tpu.sim import build_simulation

pytestmark = pytest.mark.quick


YAML = """
general:
  stop_time: 4
  seed: 13
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 1024
  events_per_host_per_window: 8
hosts:
  peer:
    quantity: 8
    app_model: phold
    app_options: {msgload: 1, runtime: 3}
"""


def _states_equal(a, b) -> bool:
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(fa, fb)
    )


def test_resume_bit_exact(tmp_path):
    ckpt = str(tmp_path / "sim.ckpt.npz")

    # uninterrupted run
    ref = build_simulation(YAML)
    ref.run()

    # run half, checkpoint, resume in a FRESH Simulation, finish
    half = build_simulation(YAML)
    half.run(until=2 * simtime.NS_PER_SEC)
    half.save_checkpoint(ckpt)

    meta = load_meta(ckpt)
    assert meta["num_hosts"] == 8

    resumed = build_simulation(YAML)
    resumed.load_checkpoint(ckpt)
    resumed.run()

    assert _states_equal(ref.state, resumed.state)
    assert ref.counters() == resumed.counters()


def test_restore_rejects_other_config(tmp_path):
    ckpt = str(tmp_path / "sim.ckpt.npz")
    sim = build_simulation(YAML)
    sim.save_checkpoint(ckpt)

    other = build_simulation(YAML.replace("quantity: 8", "quantity: 4"))
    with pytest.raises(CheckpointError, match="hosts"):
        other.load_checkpoint(ckpt)
