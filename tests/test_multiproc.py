"""Multithreaded and multi-process managed apps (docs/multiproc_design.md;
reference analogs: thread_preload.c:358-400 clone bootstrap, futex.c,
process.c fork). Each pthread gets its own driver channel; at most one
thread of a process runs app code between syscalls, making the schedule —
and therefore output — deterministic. Contended pthread mutex/cond waits
park in the DRIVER (never natively), and fork children adopt pre-created
channels and are reaped through the driver-emulated waitpid."""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)

NS = 1_000_000_000


def _yaml(path, args=""):
    arg_line = f"\n        args: {args}" if args else ""
    return f"""
general:
  stop_time: 30 s
  seed: 5
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" ]
      ]
hosts:
  solo:
    processes:
      - path: {path}{arg_line}
        start_time: 1 s
"""


def test_pthreads_pingpong_deterministic(apps):
    """3 threads pass a token via interposed mutex+cond, each sleeping
    10ms on the virtual clock; join returns every thread's value."""
    def run_once():
        d = build_process_driver(_yaml(apps["pthreads_pingpong"], "3 2"))
        d.run()
        p = d.procs[0]
        assert p.exit_code == 0, (p.stdout, p.stderr)
        return p.stdout

    out = run_once()
    lines = out.decode().splitlines()
    # token order is fixed: t0 r0, t1 r0, t2 r0, t0 r1, t1 r1, t2 r1
    order = [ln.split(" at ")[0] for ln in lines[:-1]]
    assert order == [
        "t0 round 0", "t1 round 0", "t2 round 0",
        "t0 round 1", "t1 round 1", "t2 round 1",
    ], lines
    # each holder sleeps 10ms of VIRTUAL time before passing the token on:
    # consecutive grabs are exactly 10ms apart starting at 1s
    times = [int(ln.split(" at ")[1]) for ln in lines[:-1]]
    assert times[0] == 1 * NS
    assert [t - times[0] for t in times] == [
        i * 10_000_000 for i in range(6)
    ], times
    assert lines[-1].startswith("joined sum 300 token 6")
    # byte-identical rerun (determinism gate)
    assert run_once() == out


def test_fork_child_talks_over_sim_network(apps):
    """fork(): the child adopts its own pre-created channel, sends UDP to
    the parent through the simulated loopback, exits 7; the parent reaps
    it via the driver-emulated waitpid."""
    d = build_process_driver(_yaml(apps["fork_talk"]))
    d.run()
    p = d.procs[0]
    assert p.exit_code == 0, (p.stdout, p.stderr)
    out = p.stdout.decode()
    assert "parent got 'child msg 0'" in out
    assert "parent got 'child msg 1'" in out
    assert "reaped pid ok status 7" in out

    # Deterministic rerun: identical lines (the parent and child share one
    # native stdout pipe in this harness, so INTERLEAVING of same-virtual-
    # instant lines is not defined — the CLI runner gives each process its
    # own stdout file, like the reference's shadow.data layout)
    d2 = build_process_driver(_yaml(apps["fork_talk"]))
    d2.run()
    assert sorted(d2.procs[0].stdout.splitlines()) == sorted(
        p.stdout.splitlines()
    )


def test_fork_exec_child_stays_managed(apps):
    """fork + execv: the exec'd image inherits the parent's seccomp filter
    (whose fd-argument tests let its fresh ld.so boot on low fds) and the
    channel; its re-LD_PRELOADed shim re-attaches, so it reads the VIRTUAL
    clock and its datagram rides the simulated loopback to the parent."""
    d = build_process_driver(
        _yaml(apps["exec_parent"], apps["exec_child"])
    )
    d.run()
    p = d.procs[0]
    assert p.exit_code == 0, (p.stdout, p.stderr)
    out = p.stdout.decode()
    assert "parent got 'hello from exec'" in out
    assert "parent done" in out
    # the exec'd child's clock read is the virtual clock (>= 1s start,
    # < 2s — wall-clock epoch would be ~1.7e9 seconds). The respawned
    # image has its own capture pipes, recorded on the fork child's
    # process record.
    all_out = b"\n".join(
        getattr(q, "stdout", b"") or b"" for q in d.procs
    ).decode()
    for ln in all_out.splitlines():
        if ln.startswith("exec_child t "):
            t = int(ln.split()[-1])
            assert 1_000_000_000 <= t < 2_000_000_000, ln
            break
    else:
        raise AssertionError(f"no exec_child line in {all_out!r}")
