"""Federated serve plane (shadow_tpu/serve/federation.py + router.py).

These tests drive the placement brain IN-PROCESS against fake peers
that speak the ServeClient surface but keep a REAL journal file in
their state-dir — so failover, work stealing and crash-mid-steal
recovery exercise the same journal replay path the production router
uses, without paying for subprocess daemons or fleet runs. The full
3-peer chaos choreography (SIGKILL a box mid-sweep, bit-identical
chains on the survivors) lives in `bench.py --federation-smoke`.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from shadow_tpu.core.supervisor import (
    PEER_HEALTHY,
    PEER_LOST,
    PEER_SUSPECT,
    ProbeLadder,
)
from shadow_tpu.serve import journal as journal_mod
from shadow_tpu.serve.client import ServeClient, ServeClientError, Shed
from shadow_tpu.serve.federation import (
    Federation,
    FederationError,
    parse_peer_spec,
    placement_score,
    split_handle,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# pure helpers: specs, handles, scores, the probe ladder
# ---------------------------------------------------------------------------


def test_parse_peer_spec_and_split_handle(tmp_path):
    name, sd = parse_peer_spec(f"p0={tmp_path}")
    assert name == "p0" and sd == str(tmp_path)
    # bare dir: name = basename
    name, sd = parse_peer_spec(str(tmp_path / "box7"))
    assert name == "box7"
    # '=' splits only once, so state dirs may contain '='
    name, sd = parse_peer_spec("p=/tmp/a=b")
    assert (name, sd) == ("p", "/tmp/a=b")
    for bad in ("a:b=/tmp/x", "=/tmp/x", "p0="):
        with pytest.raises(FederationError):
            parse_peer_spec(bad)
    assert split_handle("p0:s000003") == ("p0", "s000003")
    with pytest.raises(FederationError):
        split_handle("s000003")


def test_probe_ladder_states_backoff_and_recovery():
    lad = ProbeLadder(lost_after=3, seed=7)
    assert lad.state == PEER_HEALTHY and lad.backoff_s() == 0.0
    assert lad.record(False) == PEER_SUSPECT
    assert lad.record(False) == PEER_SUSPECT
    b2 = lad.backoff_s()
    assert lad.record(False) == PEER_LOST
    b3 = lad.backoff_s()
    # jittered exponential: later rungs wait longer, bounded by the cap
    assert 0.0 < b2 and b3 <= lad.backoff_cap_s * 1.5
    # one good probe snaps straight back (recovery is instant)
    assert lad.record(True) == PEER_HEALTHY
    assert lad.misses == 0 and lad.backoff_s() == 0.0
    # deterministic under a fixed seed
    a = ProbeLadder(lost_after=3, seed=1)
    b = ProbeLadder(lost_after=3, seed=1)
    a.record(False), b.record(False)
    assert a.backoff_s() == b.backoff_s()


def _health(depth=0, running=None, wait=0, chips=(8, 8), headroom=1 << 30,
            draining=False, load=None):
    return {
        "ok": True,
        "draining": draining,
        "queue": {"depth": depth, "running": running, "sweeps": {}},
        "retry_after_s": wait,
        "mesh": {"chips_total": chips[0], "chips_up": chips[1]},
        "memory": {"headroom_bytes": headroom},
        "steal": {
            "queued_predicted_load": float(depth if load is None else load),
        },
        "journal": {"records": 0, "lag": 0, "torn_tail_dropped": False},
    }


def test_placement_score_ordering():
    idle = placement_score(_health())
    loaded = placement_score(_health(depth=3, wait=6))
    assert idle == 0.0 < loaded
    # a degraded mesh runs slower: same queue scores worse at 7/8 chips
    assert placement_score(_health(depth=2, chips=(8, 7))) > \
        placement_score(_health(depth=2))
    # meshless / draining peers can never win
    assert placement_score(_health(chips=(8, 0))) == float("inf")
    assert placement_score(_health(draining=True)) == float("inf")
    # exhausted memory headroom outranks any queue difference
    assert placement_score(_health(headroom=0)) > \
        placement_score(_health(depth=8, wait=60))


# ---------------------------------------------------------------------------
# the fake peer: ServeClient surface over a REAL journal file
# ---------------------------------------------------------------------------


class FakePeer:
    """A serve daemon stand-in: same client methods, same journal
    discipline (SUBMIT / HANDOFF / COMPLETE appended to a real
    `journal.wal`), none of the fleet. `dead=True` makes every call
    raise like a connection refusal would."""

    def __init__(self, state_dir: str):
        self.state_dir = str(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.wal = journal_mod.Journal(
            os.path.join(self.state_dir, "journal.wal")
        )
        self.seq = 0
        self.dead = False
        self.draining = False
        self.shed_next = 0  # shed this many submits before accepting

    # -- test-side controls ------------------------------------------------

    def _folded(self):
        return journal_mod.JournalState(self.wal.records)

    def queued_sids(self):
        return [s["id"] for s in self._folded().unfinished()
                if s["status"] == "queued"]

    def complete(self, sid: str, ok: bool = True, results=None):
        self.wal.append(
            journal_mod.COMPLETE, id=sid, ok=ok,
            results=results or [{"name": sid, "audit": {"chain": "c" * 8}}],
        )

    # -- the ServeClient surface ------------------------------------------

    def _check(self):
        if self.dead:
            raise ServeClientError(f"{self.state_dir}: daemon unreachable")

    def health(self):
        self._check()
        depth = len(self.queued_sids())
        return _health(depth=depth, wait=depth, draining=self.draining)

    def journal(self):
        self._check()
        return {"records": self.wal.records,
                "torn_tail_dropped": self.wal.torn_tail_dropped}

    def submit(self, doc, tenant="default", backend_faults=None,
               origin=None):
        self._check()
        if self.shed_next > 0:
            self.shed_next -= 1
            return {"shed": "queue_full", "retry_after_s": 5}
        if origin is not None:
            for s in self._folded().sweeps.values():
                if s.get("origin") == origin:
                    return {"id": s["id"], "duplicate": True}
        sid = f"s{self.seq:06d}"
        self.seq += 1
        extra = {"origin": origin} if origin is not None else {}
        self.wal.append(
            journal_mod.SUBMIT, id=sid, tenant=tenant, doc=doc,
            backend_faults=backend_faults or [], **extra,
        )
        return {"id": sid, "jobs": 1, "queue_position": 0}

    def sweeps(self):
        self._check()
        st = self._folded()
        return [{"id": s["id"], "tenant": s["tenant"],
                 "status": s["status"]}
                for s in (st.sweeps[sid] for sid in st.order)]

    def sweep(self, sid):
        self._check()
        s = self._folded().sweeps.get(sid)
        if s is None:
            raise ServeClientError(f"no sweep {sid}")
        return {k: v for k, v in s.items() if k != "doc"}

    def release(self, sid, to_peer):
        self._check()
        s = self._folded().sweeps.get(sid)
        if s is None:
            raise ServeClientError(f"no sweep {sid}")
        if s["status"] != "queued":
            raise Shed({"shed": "busy", "retry_after_s": 1})
        self.wal.append(journal_mod.HANDOFF, id=sid,
                       to_peer=str(to_peer))
        return {"id": sid, "tenant": s["tenant"], "doc": s["doc"],
                "backend_faults": s.get("backend_faults") or []}

    def drain(self):
        self._check()
        self.draining = True
        return {"draining": True}

    def metrics(self):
        self._check()
        return {"counters": {}}



class Fleet:
    """N fake peers + an in-process Federation on a fake clock."""

    def __init__(self, tmp_path, n=2, lost_after=3, seed=0):
        self.clk = [100.0]
        self.fakes = {}
        specs = []
        for i in range(n):
            sd = str(tmp_path / f"p{i}")
            self.fakes[sd] = FakePeer(sd)
            specs.append(f"p{i}={sd}")
        self.journal = journal_mod.Journal(str(tmp_path / "router.wal"))
        self.fed = Federation(
            specs, self.journal, lost_after=lost_after, seed=seed,
            probe_interval_s=1.0,
            client_factory=lambda sock: self.fakes[os.path.dirname(sock)],
            now=lambda: self.clk[0],
        )

    def fake(self, name):
        return self.fakes[self.fed.peers[name].state_dir]

    def probe(self, times=1, step=30.0):
        lost = []
        for _ in range(times):
            lost += self.fed.probe_once()
            self.clk[0] += step
        return lost


DOC = {"sweep": {"name": "x"}, "general": {"seed": 1}}


def test_register_records_and_duplicate_name_refused(tmp_path):
    fl = Fleet(tmp_path, n=2)
    regs = [r for r in fl.journal.records
            if r["type"] == journal_mod.REGISTER]
    assert sorted(r["name"] for r in regs) == ["p0", "p1"]
    # REGISTER is deduplicated across router restarts
    fl.journal.close()
    j2 = journal_mod.Journal(str(tmp_path / "router.wal"))
    Federation([f"p0={tmp_path}/p0", f"p1={tmp_path}/p1"], j2,
               client_factory=lambda s: FakePeer(os.path.dirname(s)))
    regs = [r for r in j2.records if r["type"] == journal_mod.REGISTER]
    assert len(regs) == 2
    with pytest.raises(FederationError, match="duplicate"):
        Federation([f"a={tmp_path}/x", f"a={tmp_path}/y"], j2,
                   client_factory=lambda s: FakePeer(os.path.dirname(s)))


def test_place_affinity_sticks_and_sheds_fall_through(tmp_path):
    fl = Fleet(tmp_path, n=2)
    fl.probe()
    out = fl.fed.place(DOC, tenant="t")
    first = out["peer"]
    assert out["id"] == f"{first}:s000000"
    # stale health still shows depth 0 everywhere: affinity re-picks the
    # same peer (sticky within AFFINITY_SLACK) instead of round-robining
    out2 = fl.fed.place(DOC, tenant="t")
    assert out2["peer"] == first
    # a fresh probe sees the pile-up; a NEW tenant goes to the idle peer
    fl.probe()
    out3 = fl.fed.place(DOC, tenant="u")
    assert out3["peer"] != first
    # a shedding best-peer falls through to the next candidate
    fl.probe()
    for f in fl.fakes.values():
        f.shed_next = 0
    fl.fake(out3["peer"]).shed_next = 99
    out4 = fl.fed.place(DOC, tenant="u2")
    assert out4["peer"] != out3["peer"]
    # every peer shedding surfaces the shed body (the router's 429)
    for f in fl.fakes.values():
        f.shed_next = 99
    assert "shed" in fl.fed.place(DOC, tenant="u3")
    # every peer DEAD is an error, not a hang
    for f in fl.fakes.values():
        f.shed_next = 0
        f.dead = True
    with pytest.raises(FederationError, match="no live peer"):
        fl.fed.place(DOC, tenant="u4")


def test_probe_ladder_declares_loss_and_failover_replays(tmp_path):
    fl = Fleet(tmp_path, n=2, lost_after=3)
    fl.probe()
    h0 = fl.fed.place(DOC, tenant="t")["id"]
    h1 = fl.fed.place(DOC, tenant="t")["id"]
    src = split_handle(h0)[0]
    survivor = [n for n in fl.fed.peers if n != src][0]
    # one sweep settles before the box dies; its journal records that
    fl.fake(src).complete(split_handle(h0)[1])
    fl.fake(src).dead = True
    lost = fl.probe(times=3)
    assert lost == [src]
    assert fl.fed.peers[src].ladder.state == PEER_LOST
    # only the UNFINISHED sweep was re-placed, onto the survivor,
    # carrying its origin handle
    assert fl.fed.counters["failovers"] == 1
    assert fl.fed.counters["replayed_sweeps"] == 1
    assert fl.fed.counters["peers_lost"] == 1
    intents = [r for r in fl.journal.records
               if r["type"] == journal_mod.HANDOFF]
    assert [r["id"] for r in intents] == [h1]
    assert intents[0]["to_peer"] == "*failover*"
    sub = [r for r in fl.fake(survivor).wal.records
           if r["type"] == journal_mod.SUBMIT]
    assert sub and sub[-1]["origin"] == h1
    peer, sid = fl.fed.locate(h1)
    assert peer.name == survivor and sid == sub[-1]["id"]
    # failing over again is a no-op: the receiver's origin-marked SUBMIT
    # is the claim, and the daemon refuses duplicate origins
    fl.fed.fail_over(src)
    assert len([r for r in fl.fake(survivor).wal.records
                if r["type"] == journal_mod.SUBMIT]) == len(sub)
    # the completed sweep still answers from the router's mirror
    info = fl.fed.mirror_sweep_info(
        fl.fed.peers[src], split_handle(h0)[1]
    )
    assert info["status"] == "done" and info["from_mirror"]


def test_failover_from_mirror_when_state_dir_died_with_the_box(tmp_path):
    fl = Fleet(tmp_path, n=2)
    fl.probe()
    h = fl.fed.place(DOC, tenant="t")["id"]
    src, sid = split_handle(h)
    survivor = [n for n in fl.fed.peers if n != src][0]
    fl.probe()  # mirrors the journal with the SUBMIT aboard
    fl.fake(src).wal.close()
    os.remove(os.path.join(fl.fed.peers[src].state_dir, "journal.wal"))
    fl.fake(src).dead = True
    assert fl.probe(times=3) == [src]
    # replay ran from the probe-time mirror, not the (gone) state-dir
    peer, new_sid = fl.fed.locate(h)
    assert peer.name == survivor
    assert fl.fed.counters["replayed_sweeps"] == 1


def test_steal_moves_newest_queued_sweep_with_full_journal_trail(tmp_path):
    fl = Fleet(tmp_path, n=2)
    fl.probe()
    handles = [fl.fed.place(DOC, tenant="t")["id"] for _ in range(3)]
    src = split_handle(handles[0])[0]
    dst = [n for n in fl.fed.peers if n != src][0]
    fl.probe()  # src shows depth 3, dst idle
    moved = fl.fed.steal_once()
    # the NEWEST queued sweep moves (the head starts on src anyway)
    assert moved == {"id": handles[-1], "from": src, "to": dst}
    assert fl.fed.counters["steals"] == 1
    # router intent, source HANDOFF, receiver origin-SUBMIT: all durable
    assert [r["id"] for r in fl.journal.records
            if r["type"] == journal_mod.HANDOFF] == [handles[-1]]
    st = fl.fake(src)._folded()
    assert st.sweeps[split_handle(handles[-1])[1]]["status"] == "handed_off"
    assert [s["id"] for s in st.handed_off()] == \
        [split_handle(handles[-1])[1]]
    sub = [r for r in fl.fake(dst).wal.records
           if r["type"] == journal_mod.SUBMIT]
    assert sub[-1]["origin"] == handles[-1]
    assert fl.fed.locate(handles[-1])[0].name == dst
    # balanced fleet: nothing further to steal this tick
    fl.probe()
    assert fl.fed.steal_once() is None


def test_steal_receiver_shed_recovers_without_dropping(tmp_path):
    fl = Fleet(tmp_path, n=2)
    fl.probe()
    handles = [fl.fed.place(DOC, tenant="t")["id"] for _ in range(3)]
    src = split_handle(handles[0])[0]
    dst = [n for n in fl.fed.peers if n != src][0]
    fl.probe()
    fl.fake(dst).shed_next = 1  # refuse AFTER the source released
    moved = fl.fed.steal_once()
    assert moved["to"] == "*recovered*"
    # the sweep lives on exactly ONE live claim somewhere in the fleet
    claims = []
    for name in fl.fed.peers:
        st = fl.fake(name)._folded()
        claims += [s for s in st.unfinished()]
    sid = split_handle(handles[-1])[1]
    assert sid not in [s["id"] for s in fl.fake(src)._folded().unfinished()]
    peer, new_sid = fl.fed.locate(handles[-1])
    assert any(s["id"] == new_sid for s in claims)


def test_recover_handoffs_settles_every_crash_point(tmp_path):
    """The crash-mid-steal matrix: router died (a) after journaling the
    intent but before the source released, (b) after the release but
    before the receiver's submit, (c) after everything landed. A
    restarted router must settle all three without duplicating or
    dropping a sweep."""
    fl = Fleet(tmp_path, n=2)
    fl.probe()
    ha = fl.fed.place(DOC, tenant="t")["id"]
    hb = fl.fed.place(DOC, tenant="t")["id"]
    hc = fl.fed.place(DOC, tenant="t")["id"]
    src = split_handle(ha)[0]
    dst = [n for n in fl.fed.peers if n != src][0]
    # (a) intent only — the source never released
    fl.journal.append(journal_mod.HANDOFF, id=ha, from_peer=src,
                      to_peer=dst)
    # (b) intent + source released, receiver never saw it
    fl.journal.append(journal_mod.HANDOFF, id=hb, from_peer=src,
                      to_peer=dst)
    fl.fake(src).release(split_handle(hb)[1], to_peer=dst)
    # (c) the full protocol landed
    fl.journal.append(journal_mod.HANDOFF, id=hc, from_peer=src,
                      to_peer=dst)
    rel = fl.fake(src).release(split_handle(hc)[1], to_peer=dst)
    fl.fake(dst).submit(rel["doc"], tenant=rel["tenant"], origin=hc)

    # "restart": a fresh Federation over the same journal + state dirs
    fl.journal.close()
    j2 = journal_mod.Journal(str(tmp_path / "router.wal"))
    fed2 = Federation(
        [f"{n}={p.state_dir}" for n, p in fl.fed.peers.items()], j2,
        client_factory=lambda sock: fl.fakes[os.path.dirname(sock)],
    )
    recovered = fed2.recover_handoffs()
    assert recovered == [hb]  # only the torn-mid-steal sweep moved
    assert fed2.counters["handoff_recoveries"] == 1
    # (a) stayed where it was: still queued on the source
    assert split_handle(ha)[1] in fl.fake(src).queued_sids()
    # (c) resolves to the receiver that already claimed it
    assert fed2.locate(hc)[0].name == dst

    def origin_subs(handle):
        return [
            (name, r["id"])
            for name in fl.fed.peers
            for r in fl.fake(name).wal.records
            if r["type"] == journal_mod.SUBMIT
            and r.get("origin") == handle
        ]

    # (b) landed EXACTLY once somewhere live (re-placement before any
    # probe may legally re-take on the source under a fresh sid), and
    # the placement map resolves the original handle to that claim
    assert len(origin_subs(hb)) == 1
    peer_b, sid_b = fed2.locate(hb)
    assert (peer_b.name, sid_b) == origin_subs(hb)[0]
    assert len(origin_subs(hc)) == 1
    # running recovery again changes nothing (idempotent)
    total_subs = sum(
        1 for name in fl.fed.peers
        for r in fl.fake(name).wal.records
        if r["type"] == journal_mod.SUBMIT
    )
    assert fed2.recover_handoffs() == []
    assert sum(
        1 for name in fl.fed.peers
        for r in fl.fake(name).wal.records
        if r["type"] == journal_mod.SUBMIT
    ) == total_subs


def test_resurrected_peer_is_told_to_release_moved_sweeps(tmp_path):
    fl = Fleet(tmp_path, n=2)
    fl.probe()
    h = fl.fed.place(DOC, tenant="t")["id"]
    src, sid = split_handle(h)
    fl.fake(src).dead = True
    assert fl.probe(times=3) == [src]
    holder = fl.fed.locate(h)[0].name
    assert holder != src
    # the box comes back and would replay its own journal, re-running a
    # sweep the federation already moved: reconciliation releases it
    fl.fake(src).dead = False
    fl.probe()
    assert fl.fed.peers[src].ladder.state == PEER_HEALTHY
    st = fl.fake(src)._folded()
    assert st.sweeps[sid]["status"] == "handed_off"
    assert st.sweeps[sid]["handoff_to"] == holder
    # reads keep resolving to the failover copy
    assert fl.fed.locate(h)[0].name == holder


def test_health_and_metrics_docs_validate(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics

    fl = Fleet(tmp_path, n=3)
    fl.probe()
    fl.fed.place(DOC, tenant="t")
    h = fl.fed.health_doc()
    assert h["ok"] and h["peers_total"] == 3 and h["peers_up"] == 3
    assert h["placements"] == 1
    doc = fl.fed.metrics_doc()
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    assert doc["schema_version"] == obs_metrics.SCHEMA_VERSION
    assert doc["counters"]["federation.placements"] == 1
    assert doc["gauges"]["federation.peers_up"] == 3
    # status rows drive `shadowctl status --peers`
    rows = fl.fed.status_rows()
    assert [r["peer"] for r in rows] == ["p0", "p1", "p2"]
    assert all(r["state"] == PEER_HEALTHY for r in rows)


# ---------------------------------------------------------------------------
# the router process surface (in-process, fake peers, real unix socket)
# ---------------------------------------------------------------------------


def test_router_http_surface_and_drain(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.serve.router import RouterOptions, ShadowRouter

    fakes = {}
    specs = []
    for i in range(2):
        sd = str(tmp_path / f"p{i}")
        fakes[sd] = FakePeer(sd)
        specs.append(f"p{i}={sd}")
    router = ShadowRouter(
        RouterOptions(
            state_dir=str(tmp_path / "router"), peers=specs,
            probe_interval_s=0.05,
        ),
        client_factory=lambda sock: fakes[os.path.dirname(sock)],
    )
    th = threading.Thread(
        target=router.serve_forever, kwargs={"install_signals": False},
    )
    th.start()
    try:
        client = ServeClient(router.opts.socket_path, timeout=10)
        health = client.wait_ready(timeout_s=30)
        assert health["peers_total"] == 2
        out = client.submit(DOC, tenant="t")
        handle = out["id"]
        assert out["peer"] in ("p0", "p1") and ":" in handle
        # reads proxy through to the owning peer, keyed by handle
        info = client.sweep(handle)
        assert info["id"] == handle and info["status"] == "queued"
        assert [s["id"] for s in client.sweeps()] == [handle]
        doc = client.metrics()
        obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
        assert doc["counters"]["federation.placements"] == 1
        # the router journal rides the same surface as a daemon's
        jd = client.journal()
        assert [r["type"] for r in jd["records"]] == \
            [journal_mod.REGISTER] * 2
        client.drain()
        # a draining router sheds placements like a draining daemon
        with pytest.raises((Shed, ServeClientError)):
            client.submit(DOC, tenant="t2")
    finally:
        router.drain()
        th.join(timeout=30)
    assert not th.is_alive()
    assert not os.path.exists(router.opts.socket_path)
    # the metrics artifact landed and validates
    mpath = os.path.join(router.opts.state_dir, "router.metrics.json")
    obs_metrics.validate_metrics_doc(json.load(open(mpath)))


def test_shadowctl_status_peers_reports_unreachable(tmp_path):
    """`shadowctl status --peers` answers one row per peer and exits 3
    when any peer is unreachable — the operator sees WHICH box is dark
    instead of a traceback from the first dead socket."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "shadowctl.py"),
         "--socket", str(tmp_path / "nope.sock"), "--retries", "0",
         "status", "--peers", f"ghost={tmp_path}/ghost"],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert proc.returncode == 3
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["peer"] == "ghost" and row["ok"] is False
