"""Multi-hop relay e2e — the tor-minimal analog (VERDICT r4 #8; reference
src/test/tor/minimal/tor-minimal.yaml + verify.sh:7-22): every stream
traverses a 3-relay chained-TCP circuit (client → entry → middle → exit
relay → server), all five legs on the device TCP machine, grep-verified
stream-success counts, deterministic across reruns.
"""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = [
    pytest.mark.skipif(
        not build_mod.toolchain_available(), reason="no native toolchain"
    ),
    # chained device-TCP circuits: the netstack compile alone blows the
    # tier-1 budget — invoke this file directly instead
    pytest.mark.slow,
]

RELAY_PORT = 9200
EXIT_PORT = 9300


def _yaml(apps, n_clients, streams, nbytes, stop=20):
    return f"""
general:
  stop_time: {stop} s
  seed: 23
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "20 ms" packet_loss 0.0 ]
      ]
experimental:
  use_device_network: true
  use_device_tcp: true
  event_capacity: 16384
  events_per_host_per_window: 8
  sockets_per_host: 64
hosts:
  relay:
    quantity: 3
    processes:
      - path: {apps["relay"]}
        args: {RELAY_PORT} 0
        stop_time: {stop - 2} s
  exit:
    quantity: 1
    processes:
      - path: {apps["circuit_server"]}
        args: {EXIT_PORT} 0
        stop_time: {stop - 2} s
  cli:
    quantity: {n_clients}
    processes:
      - path: {apps["circuit_client"]}
        args: relay1 {RELAY_PORT} relay2:{RELAY_PORT}/relay3:{RELAY_PORT}/exit:{EXIT_PORT}/ {streams} {nbytes}
        start_time: 1 s
"""


def _run(apps, n_clients=4, streams=2, nbytes=4096):
    d = build_process_driver(_yaml(apps, n_clients, streams, nbytes))
    d.run()
    return d


def test_relay_circuits_all_streams_succeed(apps):
    n_clients, streams = 4, 2
    d = _run(apps, n_clients, streams)
    clients = [p for p in d.procs if "circuit_client" in p.args[0]]
    assert len(clients) == n_clients
    success = sum(
        p.stdout.decode().count("stream-success") for p in clients
    )
    assert success == n_clients * streams, [
        (p.name, p.stdout.decode(), p.stderr.decode()) for p in clients
    ]
    # every relay carried traffic
    relays = [p for p in d.procs if "relay" in p.args[0].rsplit("/", 1)[-1]]
    assert len(relays) == 3
    # exit server actually served the circuits
    exits = [p for p in d.procs if "circuit_server" in p.args[0]]
    assert f"served {n_clients * streams}" in exits[0].stdout.decode()


@pytest.mark.nightly
def test_relay_256_hosts_device_plane(apps):
    """Scale gate (VERDICT r4 #7): the tor analog at 256 hosts with every
    circuit leg on the DEVICE TCP machine — 9 relays (tor-minimal's count),
    2 exits, the rest circuit clients round-robining distinct 3-relay
    chains (chain builder shared with tools/run_relay.py). Nightly: ~256
    real processes + the device netstack compile."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "run_relay", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "run_relay.py",
        )
    )
    run_relay = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(run_relay)

    n_relays, n_exits, streams, nbytes, stop = 9, 2, 1, 2048, 30
    n_clients = 256 - n_relays - n_exits
    chains = run_relay.circuit_host_blocks(
        n_clients, n_relays, n_exits, apps["circuit_client"], streams, nbytes
    )
    yaml = f"""
general:
  stop_time: {stop} s
  seed: 31
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "20 ms" packet_loss 0.0 ]
      ]
experimental:
  use_device_network: true
  use_device_tcp: true
  event_capacity: {1 << 16}
  events_per_host_per_window: 8
  # each relay transits ~n_clients*3/n_relays circuits at 2 sockets per
  # transit (held open through the run), and each exit accepts
  # ~n_clients/n_exits streams: 128 capped success at exactly 128/245
  sockets_per_host: 512
hosts:
  relay:
    quantity: {n_relays}
    processes:
      - path: {apps["relay"]}
        args: {RELAY_PORT} 0
        stop_time: {stop - 2} s
  exit:
    quantity: {n_exits}
    processes:
      - path: {apps["circuit_server"]}
        args: {EXIT_PORT} 0
        stop_time: {stop - 2} s
{chains}
"""
    d = build_process_driver(yaml)
    d.run()
    clients = [p for p in d.procs if "circuit_client" in p.args[0]]
    assert len(clients) == n_clients
    success = sum(
        p.stdout.decode().count("stream-success") for p in clients
    )
    assert success == n_clients * streams, (
        f"{success}/{n_clients * streams} streams; first failures: "
        + str([
            (p.name, p.stdout.decode()[-200:], p.stderr.decode()[-200:])
            for p in clients if b"stream-success" not in p.stdout
        ][:3])
    )


def test_relay_circuits_deterministic(apps):
    """tor-minimal's determinism bar (determinism1_compare.cmake analog):
    two identical runs produce byte-identical client output."""
    a = _run(apps, n_clients=2, streams=2, nbytes=2048)
    b = _run(apps, n_clients=2, streams=2, nbytes=2048)

    def outs(d):
        return sorted(
            (p.name, p.stdout) for p in d.procs
            if "circuit_client" in p.args[0]
        )

    assert outs(a) == outs(b)
    assert sum(o[1].count(b"stream-success") for o in outs(a)) == 4
