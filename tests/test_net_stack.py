"""Network-stack tests: token buckets, CoDel, UDP echo/flood end-to-end."""

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import simtime
from shadow_tpu.net import codel, nic, packet as pkt
from shadow_tpu.sim import build_simulation

MS = simtime.NS_PER_MS
SEC = simtime.NS_PER_SEC

GML_2V = """
graph [
  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  node [ id 1 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]
  edge [ source 0 target 0 latency "1 ms" ]
  edge [ source 1 target 1 latency "1 ms" ]
  edge [ source 0 target 1 latency "50 ms" packet_loss 0.0 ]
]
"""


# ---------------------------------------------------------------------------
# unit: token bucket lazy refill
# ---------------------------------------------------------------------------


def test_lazy_refill_grid():
    rem = jnp.asarray([0, 500], dtype=jnp.int64)
    tick = jnp.asarray([0, 0], dtype=jnp.int64)
    refill = jnp.asarray([1000, 1000], dtype=jnp.int64)
    cap = refill + pkt.MTU
    # at t = 3.5ms, 3 grid ticks elapsed → +3000, clamped to cap
    new_rem, new_tick = nic.lazy_refill(rem, tick, refill, cap, jnp.int64(3_500_000))
    assert list(new_rem) == [min(3000, 2500), 2500]
    assert list(new_tick) == [3, 3]
    # no time passed → unchanged
    r2, t2 = nic.lazy_refill(new_rem, new_tick, refill, cap, jnp.int64(3_600_000))
    assert list(r2) == list(new_rem)


def test_next_refill_time():
    assert int(nic.next_refill_time(jnp.int64(0))) == MS
    assert int(nic.next_refill_time(jnp.int64(MS - 1))) == MS
    assert int(nic.next_refill_time(jnp.int64(MS))) == 2 * MS


# ---------------------------------------------------------------------------
# unit: CoDel dequeue law
# ---------------------------------------------------------------------------


def _mk_router(H=1, Q=32):
    return codel.init(H, Q)


def _payload(size=1472):
    p = jnp.zeros((1, 12), dtype=jnp.int32)
    p = p.at[0, pkt.W_PROTO].set(pkt.PROTO_UDP)
    p = p.at[0, pkt.W_LEN].set(size)
    return p


def test_codel_below_target_no_drops():
    r = _mk_router()
    mask = jnp.asarray([True])
    src = jnp.asarray([0], dtype=jnp.int32)
    t = 0
    for i in range(5):
        r = codel.enqueue(r, mask, _payload(), src, jnp.int64(t))
    # dequeue immediately: sojourn 0 → all delivered
    got = 0
    for i in range(5):
        r, have, payload, s = codel.dequeue(r, jnp.int64(t + 1 * MS), mask)
        got += int(have[0])
    assert got == 5
    assert int(r.codel_dropped) == 0


def test_codel_sustained_delay_drops():
    """Packets sojourning > 10ms for over 100ms trigger drop mode."""
    r = _mk_router(Q=64)
    mask = jnp.asarray([True])
    src = jnp.asarray([0], dtype=jnp.int32)
    # enqueue 40 packets at t=0
    for i in range(40):
        r = codel.enqueue(r, mask, _payload(), src, jnp.int64(0))
    # dequeue one per 10ms starting at t=50ms: sojourn always > 10ms (bad
    # state). First interval arms at 50ms, expires at 150ms; from then on
    # packets start dropping.
    delivered, times = 0, []
    t = 50 * MS
    while True:
        r, have, payload, s = codel.dequeue(r, jnp.int64(t), mask)
        if not bool(codel.nonempty(r)[0]) and not bool(have[0]):
            break
        if bool(have[0]):
            delivered += 1
            times.append(t)
        t += 10 * MS
    dropped = int(r.codel_dropped)
    assert dropped > 0, "sustained over-target sojourn must drop"
    assert delivered + dropped == 40
    # before the interval expired (t < 150ms) nothing was dropped
    assert times[:10] == [50 * MS + i * 10 * MS for i in range(10)]


def test_codel_fresh_packet_ends_drop_mode():
    """Regression: in drop mode, dropping a stale packet and popping a FRESH
    (low-sojourn) one must deliver the fresh packet and exit drop mode — the
    fresh packet must be judged by its own sojourn, not its predecessor's."""
    r = _mk_router(Q=8)
    mask = jnp.asarray([True])
    src = jnp.asarray([0], dtype=jnp.int32)
    r = codel.enqueue(r, mask, _payload(), src, jnp.int64(0))  # A, stale
    r = codel.enqueue(r, mask, _payload(), src, jnp.int64(199 * MS))  # B, fresh
    r = r.replace(
        drop_mode=jnp.asarray([True]),
        next_drop=jnp.asarray([200 * MS], dtype=jnp.int64),
        interval_expire=jnp.asarray([150 * MS], dtype=jnp.int64),
    )
    r, have, payload, s = codel.dequeue(r, jnp.int64(200 * MS), mask)
    assert bool(have[0]), "fresh packet B must be delivered"
    assert int(r.codel_dropped) == 1  # only stale A dropped
    assert not bool(r.drop_mode[0]), "low sojourn must exit drop mode"


def test_codel_queue_overflow_counted():
    r = _mk_router(Q=4)
    mask = jnp.asarray([True])
    src = jnp.asarray([0], dtype=jnp.int32)
    for i in range(6):
        r = codel.enqueue(r, mask, _payload(), src, jnp.int64(0))
    assert int(r.overflow_dropped) == 2


# ---------------------------------------------------------------------------
# e2e: UDP echo RTT through the full stack
# ---------------------------------------------------------------------------


def _echo_cfg(interval="200 ms", runtime=2, stop=4, size=512):
    return {
        "general": {"stop_time": stop, "seed": 5},
        "network": {"graph": {"type": "gml", "inline": GML_2V}},
        "experimental": {"event_capacity": 4096, "events_per_host_per_window": 8},
        "hosts": {
            "server": {
                "network_node_id": 0,
                "app_model": "udp_echo",
                "app_options": {"role": "server"},
            },
            "client": {
                "network_node_id": 1,
                "app_model": "udp_echo",
                "app_options": {
                    "interval": interval,
                    "runtime": runtime,
                    "size": size,
                },
            },
        },
    }


def test_udp_echo_rtt():
    sim = build_simulation(_echo_cfg())
    sim.run()
    sub = jax.device_get(sim.state.subs["udp_echo"])
    # hosts sorted by name: client=0, server=1 → roles: client at index 0
    ci = [i for i, h in enumerate(sim.config.hosts) if h.name == "client"][0]
    si = [i for i, h in enumerate(sim.config.hosts) if h.name == "server"][0]
    sent = int(sub["sent"][ci])
    echoed = int(sub["echoed"][si])
    rtt_count = int(sub["rtt_count"][ci])
    assert sent >= 10
    assert echoed == sent  # unloaded, lossless: everything echoes
    assert rtt_count == sent
    # RTT = exactly 2 × 50ms path latency (ample tokens, empty queues)
    avg_rtt = int(sub["rtt_sum"][ci]) / rtt_count
    assert avg_rtt == 2 * 50 * MS, f"avg rtt {avg_rtt}"
    c = sim.counters()
    assert c["pool_overflow_dropped"] == 0
    assert c["outbox_overflow_dropped"] == 0


def test_udp_echo_deterministic():
    a = build_simulation(_echo_cfg())
    b = build_simulation(_echo_cfg())
    a.run()
    b.run()
    assert a.counters() == b.counters()
    sa = jax.device_get(a.state.subs["udp_echo"])
    sb = jax.device_get(b.state.subs["udp_echo"])
    assert list(sa["rtt_sum"]) == list(sb["rtt_sum"])


# ---------------------------------------------------------------------------
# e2e: UDP flood with a rate-limited sender (token-bucket pacing)
# ---------------------------------------------------------------------------


def test_udp_flood_paced_and_conserved():
    # client bw_up = 12 Mbit → 1500 B/ms refill; wire size 1500 → steady
    # state 1 packet/ms after an initial 2-packet burst (cap = refill + MTU).
    cfg = {
        "general": {"stop_time": 3, "seed": 3},
        "network": {"graph": {"type": "gml", "inline": GML_2V}},
        "experimental": {"event_capacity": 8192, "events_per_host_per_window": 8},
        "hosts": {
            "server": {
                "network_node_id": 0,
                "app_model": "udp_flood",
                "app_options": {"role": "server"},
            },
            "client": {
                "network_node_id": 1,
                "bandwidth_up": "12 Mbit",
                "app_model": "udp_flood",
                "app_options": {
                    "interval": "250 us",
                    "runtime": "20 ms",
                    "size": 1472,
                },
            },
        },
    }
    sim = build_simulation(cfg)
    sim.run()
    sub = jax.device_get(sim.state.subs["udp_flood"])
    ci = [i for i, h in enumerate(sim.config.hosts) if h.name == "client"][0]
    si = [i for i, h in enumerate(sim.config.hosts) if h.name == "server"][0]
    sent = int(sub["sent"][ci])
    recv = int(sub["recv"][si])
    assert sent == 80  # 20ms / 250us
    n = jax.device_get(sim.state.subs["nic"])
    ring_left = int(n.q_tail[ci] - n.q_head[ci])
    ring_dropped = int(n.sendq_dropped)
    # conservation: all sent packets are delivered, still queued, or dropped
    assert recv + ring_left + ring_dropped == sent
    # pacing: after the 2-packet burst, at most 1 packet/ms leaves the NIC.
    # From first send (t=1s) to stop (t=3s) ≈ 2000 refills max.
    assert recv <= 2 + 2000
    # the 2-second drain at 1 pkt/ms empties far more than the burst
    assert recv > 40
    c = sim.counters()
    assert c["packets_delivered"] == recv
    u = jax.device_get(sim.state.subs["udp"])
    assert int(u.drop_no_socket) == 0


def test_loopback_bypasses_router():
    """Self-addressed traffic must not consume router/bucket resources."""
    cfg = {
        "general": {"stop_time": 2, "seed": 1},
        "network": {
            "graph": {
                "type": "gml",
                "inline": (
                    'graph [ node [ id 0 bandwidth_down "1 Mbit" '
                    'bandwidth_up "1 Mbit" ] '
                    'edge [ source 0 target 0 latency "1 ms" ] ]'
                ),
            }
        },
        "experimental": {"event_capacity": 1024},
        "hosts": {
            "server": {"app_model": "udp_echo", "app_options": {"role": "server"}},
            "client": {
                "app_model": "udp_echo",
                "app_options": {"interval": "100 ms", "runtime": 1},
            },
        },
    }
    sim = build_simulation(cfg)
    sim.run()
    sub = jax.device_get(sim.state.subs["udp_echo"])
    assert int(sub["echoed"].sum()) == int(sub["sent"].sum())


def test_rr_qdisc_service_order():
    """Round-robin-over-sockets qdisc (network_queuing_disciplines.c RR):
    queue [s0, s0, s0, s1] services as s0, s1, s0, s0."""
    import jax.numpy as jnp

    from shadow_tpu.net import nic, packet as pkt

    H, NQ, S = 2, 8, 4
    bw = jnp.full((H,), 10**9, jnp.int64)
    n = nic.init(bw, bw, NQ)

    def mk(sock):
        return pkt.make_udp(
            src_port=jnp.full((H,), 1000, jnp.int32),
            dst_port=jnp.full((H,), 2000, jnp.int32),
            length=jnp.full((H,), 100, jnp.int32),
            priority=jnp.zeros((H,), jnp.int32),
            src_host=jnp.arange(H, dtype=jnp.int32),
            socket_slot=jnp.full((H,), sock, jnp.int32),
        )

    mask = jnp.array([True, False])
    for sock in [0, 0, 0, 1]:
        n, ok = nic.enqueue_send(n, mask, jnp.zeros((H,), jnp.int32), mk(sock))
        assert bool(ok[0])
    order = []
    for _ in range(4):
        payload, dst, has, slot = nic.peek_send_rr(n, S)
        assert bool(has[0])
        order.append(int(payload[0, pkt.W_SOCKET]))
        n = nic.pop_send_rr(n, has, slot)
    assert order == [0, 1, 0, 0], order
    _, _, has, _ = nic.peek_send_rr(n, S)
    assert not bool(has[0])
    # untouched host's queue is untouched
    assert int(n.q_head[1]) == 0 and int(n.q_tail[1]) == 0


def test_rr_qdisc_sim_conserves_packets():
    """phold-rr-qdisc analog: a flood sim under interface_qdisc=roundrobin
    delivers the same packet totals as fifo."""
    from shadow_tpu.sim import build_simulation

    def run(qdisc):
        sim = build_simulation(f"""
general:
  stop_time: 2
  seed: 6
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 2048
  events_per_host_per_window: 8
  interface_qdisc: {qdisc}
hosts:
  server:
    app_model: udp_flood
    app_options: {{role: server}}
  client:
    quantity: 3
    app_model: udp_flood
    app_options: {{interval: "50 ms", size: 400, runtime: 1}}
""")
        sim.run()
        return sim.counters()

    fifo = run("fifo")
    rr = run("roundrobin")
    assert rr["packets_delivered"] == fifo["packets_delivered"] > 0
    assert rr["bytes_delivered"] == fifo["bytes_delivered"]


import pytest as _pytest


@_pytest.mark.parametrize("variant", ["static", "single"])
def test_router_queue_variants(variant):
    """The non-AQM router variants (router_queue_static.c /
    router_queue_single.c analogs): a drop-tail FIFO (1-slot ring for
    "single") still delivers traffic end to end; CoDel's control law is
    bypassed."""
    from shadow_tpu.sim import build_simulation

    sim = build_simulation({
        "general": {"stop_time": 3, "seed": 9},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "10 Mbit" bandwidth_up "10 Mbit" ]\n'
            '  edge [ source 0 target 0 latency "10 ms" ]\n]\n')}},
        "experimental": {
            "event_capacity": 2048,
            "events_per_host_per_window": 8,
            "router_queue_variant": variant,
            "router_queue_slots": 8,
        },
        "hosts": {
            "server": {"quantity": 1, "app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": 7, "app_model": "udp_flood",
                       "app_options": {"interval": "50 ms", "size": 512,
                                       "runtime": 2}},
        },
    })
    sim.run_stepwise()
    c = sim.counters()
    assert c["packets_delivered"] > 100
    assert c["pool_overflow_dropped"] == 0


def test_gated_arrival_batching_equivalence():
    """Gated bulk batching of KIND_PKT_DELIVER (the reference drains a
    whole arrival burst in one receivePackets task) must be INVISIBLE in
    every observable: counters, app state, NIC/router/UDP state — only
    micro_steps (iteration count) may differ. Covers contended hosts too:
    the flood drives servers at 8 clients each through a lossy path."""
    def cfg(seed):
        return {
            "general": {"stop_time": 3, "seed": seed},
            "network": {"graph": {"type": "gml", "inline": (
                'graph [\n'
                '  node [ id 0 bandwidth_down "3 Mbit" '
                'bandwidth_up "3 Mbit" ]\n'
                '  edge [ source 0 target 0 latency "10 ms" '
                'packet_loss 0.01 ]\n]\n')}},
            "experimental": {"event_capacity": 8192,
                             "events_per_host_per_window": 16,
                             "outbox_slots": 24,
                             "router_queue_slots": 16, "inbox_slots": 4},
            "hosts": {
                "server": {"quantity": 4, "app_model": "udp_flood",
                           "app_options": {"role": "server"}},
                "client": {"quantity": 28, "app_model": "udp_flood",
                           "app_options": {"interval": "5 ms", "size": 1024,
                                           "runtime": 1}},
            },
        }

    sim_b = build_simulation(cfg(21))  # batched (deliver_batch=8 default)
    from shadow_tpu.net.stack import NetStack  # noqa: F401
    sim_1 = build_simulation(cfg(21))
    # rebuild sim_1's kernel with batching off
    from shadow_tpu.core.engine import Simulation as _S  # noqa: F401
    import shadow_tpu.net.stack as stack_mod

    orig = stack_mod.NetStack.bulk_kinds
    try:
        stack_mod.NetStack.bulk_kinds = lambda self: None
        sim_1 = build_simulation(cfg(21))
    finally:
        stack_mod.NetStack.bulk_kinds = orig

    sim_b.run()
    sim_1.run()
    cb, c1 = sim_b.counters(), sim_1.counters()
    assert cb["micro_steps"] <= c1["micro_steps"]  # batching only helps
    for k in cb:
        if k != "micro_steps":
            assert cb[k] == c1[k], (k, cb[k], c1[k])
    for sub in ("udp_flood", "udp", "nic", "router"):
        a = jax.device_get(sim_b.state.subs[sub])
        b = jax.device_get(sim_1.state.subs[sub])
        af = a if isinstance(a, dict) else a.__dict__
        bf = b if isinstance(b, dict) else b.__dict__
        for f in af:
            assert np.array_equal(np.asarray(af[f]), np.asarray(bf[f])), \
                (sub, f)
