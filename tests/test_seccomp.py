"""Seccomp/SIGSYS backstop (native/shim/shim.cpp; reference analog
shim.c:399-463): RAW syscall instructions — issued via libc's syscall(2),
which bypasses every interposed symbol — are trapped by the BPF filter and
routed through the simulator. The app below uses ONLY raw syscalls for
sockets, sleep, and the clock, so it passes iff the backstop works: without
it, raw clock_gettime returns wall-clock epoch time and the raw sockets
would need a real network.
"""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)

NS = 1_000_000_000


def _yaml(apps, seccomp=True):
    return f"""
general:
  stop_time: 30 s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "30 ms" ]
      ]
experimental:
  use_seccomp: {str(seccomp).lower()}
hosts:
  server:
    ip_address_hint: 11.0.0.1
    processes:
      - path: {apps['raw_syscalls']}
        args: --server 9000 2
  client:
    processes:
      - path: {apps['raw_syscalls']}
        args: 11.0.0.1 9000 2
        start_time: 1 s
"""


def test_raw_syscalls_are_virtualized(apps):
    """Raw clock_gettime/nanosleep/socket/sendto/recvfrom all ride the
    simulator: the printed times are exact virtual-clock values."""
    d = build_process_driver(_yaml(apps))
    d.run()
    client = next(p for p in d.procs if "--server" not in p.args)
    server = next(p for p in d.procs if "--server" in p.args)
    assert client.exit_code == 0, (client.stdout, client.stderr)
    assert server.exit_code == 0, (server.stdout, server.stderr)
    lines = client.stdout.decode().splitlines()
    # t0 = process start time (1 s), proving the raw clock is virtual
    assert lines[0] == f"t0 {1 * NS}"
    # echo i arrives at 1s + (i+1)*250ms sleep + 60ms round trip
    assert lines[1] == f"echo 0 at {int(1.31 * NS)}"
    assert lines[2] == f"echo 1 at {int(1.62 * NS)}"
    assert b"served 2" in server.stdout


def _single_host_yaml(path):
    return f"""
general:
  stop_time: 10 s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "30 ms" ]
      ]
hosts:
  solo:
    processes:
      - path: {path}
        start_time: 1 s
"""


def test_vdso_clock_is_neutralized(apps):
    """A direct call into the vDSO's __vdso_clock_gettime — which never
    enters the kernel and so is invisible to both libc interposition and
    seccomp — must still read the VIRTUAL clock. The shim patches the vDSO
    entry points into real syscall instructions at init (shim_patch_vdso);
    this is the regression test for the ADVICE r1 vDSO determinism gap."""
    d = build_process_driver(_single_host_yaml(apps["vdso_time"]))
    d.run()
    p = d.procs[0]
    assert p.exit_code == 0, (p.stdout, p.stderr)
    lines = p.stdout.decode().splitlines()
    # virtual clock at process start (1 s), not wall-clock epoch time
    assert lines[0] == f"vdso t0 {1 * NS}"
    # the 100ms nanosleep advances the vDSO-read clock by exactly 100ms
    assert lines[1] == f"vdso dt {100_000_000}"
