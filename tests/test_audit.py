"""Determinism audit plane (ISSUE 5): in-kernel digest chains, the
virtual-time flight recorder, and the divergence bisector.

The load-bearing property is CHAIN PARITY across the whole engine matrix
— conservative vs optimistic, global vs islands, fleet lane vs solo,
checkpoint/resume vs uninterrupted — asserted on the per-host digest rows
(order-dependent per host) and the combined chain (order-independent
across hosts). Plus the host-side surfaces: the digest document +
validator, tools/diff_digest.py pinpointing a forged divergence, the
flight-recorder ring/spool/trace pipeline, and the sweep CLI path with
--metrics-out/--trace-out (schema v5, per-lane trace tids).
"""

import copy
import importlib.util
import json
import pathlib

import jax
import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.obs import audit as audit_mod
from shadow_tpu.obs import flight as flight_mod
from shadow_tpu.sim import build_simulation

NS_PER_SEC = 1_000_000_000

_UDP_GML = """\
graph [
  node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
  edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ]
]
"""

_PHOLD_GML = """\
graph [
  node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
  edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
]
"""


def _udp_cfg(**exp):
    """Tiny udp_flood scenario (loop-path windows): 1 server + 3 clients."""
    return {
        "general": {"stop_time": 3, "seed": 2},
        "network": {"graph": {"type": "gml", "inline": _UDP_GML}},
        "experimental": {
            "event_capacity": 2048,
            "events_per_host_per_window": 8,
            **exp,
        },
        "hosts": {
            "server": {"app_model": "udp_flood",
                       "app_options": {"role": "server"}},
            "client": {"quantity": 3, "app_model": "udp_flood",
                       "app_options": {"interval": "100 ms", "size": 600,
                                       "runtime": 1}},
        },
    }


def _phold_cfg(seed=7, stop="1.5 s", hosts=8, **exp):
    """Tiny PHOLD scenario (matrix-path windows)."""
    return {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "gml", "inline": _PHOLD_GML}},
        "experimental": {
            "event_capacity": 1024,
            "events_per_host_per_window": 8,
            "outbox_slots": 8,
            "inbox_slots": 4,
            **exp,
        },
        "hosts": {
            "peer": {
                "quantity": hosts,
                "app_model": "phold",
                "app_options": {"msgload": 2, "runtime": 2,
                                "start_time": "100 ms"},
            }
        },
    }


def _digests(sim):
    snap = sim.obs_snapshot()
    return snap["host_digest"], audit_mod.combine(snap["host_digest"])


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, pathlib.Path(__file__).parent.parent / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# chain parity across the engine matrix
# ---------------------------------------------------------------------------


def test_digest_parity_conservative_vs_optimistic():
    cons = build_simulation(_udp_cfg())
    cons.run()
    opt = build_simulation(_udp_cfg())
    opt.run_optimistic()
    dc, cc = _digests(cons)
    do, co = _digests(opt)
    assert np.any(dc != 0), "digest chain never folded"
    assert np.array_equal(dc, do)
    assert cc == co != 0


def test_digest_parity_global_vs_islands():
    g = build_simulation(_udp_cfg())
    g.run()
    i = build_simulation(_udp_cfg(num_shards=2, exchange_slots=16))
    i.run()
    dg, cg = _digests(g)
    di, ci = _digests(i)
    # per-host sub-chains come back in GLOBAL host order; the combine is
    # order-independent, so shard layout cannot move the value
    assert np.array_equal(dg, di)
    assert cg == ci != 0


def test_digest_parity_islands_conservative_vs_optimistic():
    a = build_simulation(_udp_cfg(num_shards=2, exchange_slots=16))
    a.run()
    b = build_simulation(_udp_cfg(num_shards=2, exchange_slots=16))
    b.run_optimistic()
    da, ca = _digests(a)
    db, cb = _digests(b)
    assert np.array_equal(da, db)
    assert ca == cb != 0


def test_digest_parity_phold_matrix_global_vs_islands():
    """PHOLD dispatches the matrix fast path (pinned under vmap islands,
    cond-selected on the global engine): a window folded by either path
    must chain identically."""
    g = build_simulation(_phold_cfg())
    g.run()
    i = build_simulation(_phold_cfg(num_shards=2, exchange_slots=16))
    i.run()
    dg, cg = _digests(g)
    di, ci = _digests(i)
    assert np.array_equal(dg, di)
    assert cg == ci != 0


def test_digest_checkpoint_resume_parity(tmp_path):
    """A run resumed from a mid-run ring checkpoint must end on the exact
    chain of the uninterrupted run, and the checkpoint header carries the
    chain at its boundary (the diff tool's --checkpoint input)."""
    from shadow_tpu.core import checkpoint as ckpt_mod

    full = build_simulation(_udp_cfg())
    full.run()
    d_full, c_full = _digests(full)

    d = tmp_path / "ring"
    part = build_simulation(_udp_cfg())
    part.configure_auto_checkpoint(str(d), NS_PER_SEC, retain=3)
    part.run(until=int(1.6 * NS_PER_SEC))
    entries = ckpt_mod.ring_entries(str(d))
    assert entries, "no ring checkpoint written"
    meta = ckpt_mod.load_meta(entries[-1][2])
    assert isinstance(meta.get("audit", {}).get("chain"), int)

    res = build_simulation(_udp_cfg())
    info = res.resume_from(str(d))
    assert info["fallbacks"] == 0
    # the restored state's chain equals the checkpoint header's
    assert res.audit_chain() == meta["audit"]["chain"]
    res.run()
    d_res, c_res = _digests(res)
    assert np.array_equal(d_full, d_res)
    assert c_full == c_res != 0


def test_digest_compiles_out_with_audit_disabled():
    sim = build_simulation(_udp_cfg(audit_digest=False))
    sim.run(until=NS_PER_SEC)
    d, c = _digests(sim)
    assert not np.any(d)
    with pytest.raises(ValueError, match="obs block"):
        build_simulation(_udp_cfg(obs_counters=False)).attach_audit()


# ---------------------------------------------------------------------------
# digest document + divergence bisector
# ---------------------------------------------------------------------------


def _run_with_trail(cfg, **run_kw):
    sim = build_simulation(cfg)
    sim.attach_audit(meta={"seed": cfg["general"]["seed"]})
    sim.run(**run_kw)
    return sim


def test_digest_document_and_validator(tmp_path):
    sim = _run_with_trail(_udp_cfg(), windows_per_dispatch=8)
    doc = sim.write_digest(str(tmp_path / "a.digest.json"))
    audit_mod.validate_digest_doc(doc)  # dump() already validated; explicit
    assert doc["records"], "no chain records at handoff boundaries"
    assert doc["final"]["chain"] == sim.audit_chain() != 0
    assert doc["final"]["events_committed"] == \
        sim.counters()["events_committed"]
    assert len(doc["hosts"]) == sim.num_hosts
    # frontiers never regress, and are clamped to the stop time
    fr = [r["frontier_ns"] for r in doc["records"]]
    assert fr == sorted(fr) and fr[-1] <= sim.stop_time
    with pytest.raises(ValueError, match="schema_version"):
        audit_mod.validate_digest_doc({**doc, "schema_version": 99})
    bad = copy.deepcopy(doc)
    del bad["records"][0]["chain"]
    with pytest.raises(ValueError, match="record"):
        audit_mod.validate_digest_doc(bad)
    with pytest.raises(ValueError, match="hosts"):
        audit_mod.validate_digest_doc({**doc, "hosts": ["x"]})


def test_diff_digest_tool_pinpoints_forged_window(tmp_path, capsys):
    """Two seeded reruns diff identical (rc 0); forging one mid-run
    record + one host sub-chain is pinpointed to the exact window and
    host (rc 1) — the full-rerun bisect collapsed to one invocation."""
    p0, p1 = tmp_path / "a.json", tmp_path / "b.json"
    _run_with_trail(_udp_cfg(), windows_per_dispatch=8).write_digest(str(p0))
    _run_with_trail(_udp_cfg(), windows_per_dispatch=8).write_digest(str(p1))
    tool = _load_tool("diff_digest")
    assert tool.main([str(p0), str(p1)]) == 0

    doc = json.loads(p1.read_text())
    k = len(doc["records"]) // 2
    assert k > 0, "need several handoff records to bisect"
    doc["records"][k]["chain"] ^= 0x5A5A
    doc["hosts"][2] ^= 0x5A5A
    forged = tmp_path / "forged.json"
    forged.write_text(json.dumps(doc))
    capsys.readouterr()
    assert tool.main([str(p0), str(forged), "--json"]) == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep["first_divergent_record"]["seq_b"] == k
    assert rep["first_divergent_record"]["frontier_ns"] == \
        doc["records"][k]["frontier_ns"]
    assert rep["divergent_hosts"] == [2]


def test_diff_digest_tool_audits_checkpoints(tmp_path):
    d = tmp_path / "ring"
    sim = build_simulation(_udp_cfg())
    sim.attach_audit()
    sim.configure_auto_checkpoint(str(d), NS_PER_SEC, retain=3)
    sim.run()
    digest = tmp_path / "run.digest.json"
    sim.write_digest(str(digest))
    tool = _load_tool("diff_digest")
    assert tool.main([str(digest), "--checkpoint", str(d)]) == 0
    # a digest from a DIFFERENT history must not match the ring (a seed
    # change alone is not enough: lossless udp_flood draws no RNG, so its
    # event stream — and therefore its chain — is seed-invariant)
    other = tmp_path / "other.digest.json"
    cfg = _udp_cfg()
    cfg["hosts"]["client"]["app_options"]["interval"] = "90 ms"
    _run_with_trail(cfg).write_digest(str(other))
    assert tool.main([str(other), "--checkpoint", str(d)]) == 1


# ---------------------------------------------------------------------------
# flight recorder: ring, spool, virtual-time trace
# ---------------------------------------------------------------------------


def test_flight_recorder_ring_and_spool(tmp_path):
    spool_path = tmp_path / "run.flight.spool"
    sim = build_simulation(_udp_cfg(flight_recorder=16))
    sim.attach_flight_spool(str(spool_path))
    sim.run_stepwise()  # per-window handoffs: every record spools
    fl = jax.device_get(sim.state.flight)
    snap = sim.obs_snapshot()
    cnt = np.asarray(fl.count)
    assert np.array_equal(cnt, snap["host_events"])
    # the newest ring record per host is the host's frontier event
    R = sim.state.flight.capacity
    t = np.asarray(fl.time)
    for h in range(sim.num_hosts):
        if cnt[h]:
            assert t[h, (cnt[h] - 1) % R] == snap["host_last_t"][h]
    sim.flight_spool.flush(sim, sim.stop_time)
    sim.flight_spool.close()
    spool = flight_mod.read_spool(str(spool_path))
    assert spool["capacity"] == 16
    assert sum(f["lost"] for f in spool["frames"]) == 0
    per_host: dict[int, list[int]] = {}
    for f in spool["frames"]:
        for host, t_ns, src, seq, kind in f["records"]:
            per_host.setdefault(host, []).append(t_ns)
    for h in range(sim.num_hosts):
        got = per_host.get(h, [])
        assert len(got) == int(cnt[h]), f"host {h} spooled {len(got)}"
        assert got == sorted(got), "per-host virtual time regressed"

    # spool -> second Perfetto clock domain (virtual-time tracks per host)
    tool = _load_tool("flight_to_trace")
    out = tmp_path / "flight.trace.json"
    assert tool.main([str(spool_path), "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    evs = doc["traceEvents"]
    names = [e for e in evs if e.get("ph") == "M"
             and e["name"] == "thread_name"]
    assert {e["tid"] for e in names} == set(per_host)
    marks = [e for e in evs if e.get("ph") == "i"]
    assert len(marks) == sum(len(v) for v in per_host.values())
    assert all(e["pid"] == 1 for e in marks)
    # merge with a wall-time trace: both clock domains in one document
    wall = tmp_path / "wall.trace.json"
    wall.write_text(json.dumps({"traceEvents": [
        {"name": "dispatch", "ph": "X", "pid": 0, "tid": 0,
         "ts": 0.0, "dur": 5.0},
    ]}))
    merged = tmp_path / "merged.trace.json"
    assert tool.main([str(spool_path), "-o", str(merged),
                      "--merge", str(wall)]) == 0
    mdoc = json.loads(merged.read_text())
    pids = {e["pid"] for e in mdoc["traceEvents"]}
    assert pids == {0, 1}


def test_flight_rollbacks_discard_speculated_records():
    """Optimistic rollbacks drop speculated ring writes with the rest of
    the speculated pytree: the committed ring equals the conservative
    run's bit-for-bit."""
    a = build_simulation(_udp_cfg(flight_recorder=16))
    a.run()
    b = build_simulation(_udp_cfg(flight_recorder=16))
    b.run_optimistic()
    fa, fb = jax.device_get(a.state.flight), jax.device_get(b.state.flight)
    assert np.array_equal(np.asarray(fa.count), np.asarray(fb.count))
    assert np.array_equal(np.asarray(fa.time), np.asarray(fb.time))
    assert np.array_equal(np.asarray(fa.src), np.asarray(fb.src))


def test_flight_requires_compiled_ring():
    sim = build_simulation(_udp_cfg())  # no flight_recorder
    with pytest.raises(ValueError, match="flight_recorder"):
        sim.attach_flight_spool("/tmp/unused.spool")


# ---------------------------------------------------------------------------
# satellites: trace_summary forms, validate_metrics CLI, sweep CLI path
# ---------------------------------------------------------------------------


def test_trace_summary_bare_array_and_json(tmp_path, capsys):
    events = [
        {"name": "dispatch", "ph": "X", "pid": 0, "tid": 0,
         "ts": 0.0, "dur": 1500.0},
        {"name": "dispatch", "ph": "X", "pid": 0, "tid": 0,
         "ts": 2000.0, "dur": 500.0},
        {"name": "rollback", "ph": "i", "pid": 0, "tid": 0, "ts": 3.0},
    ]
    mod = _load_tool("trace_summary")
    rows, other = mod.summarize(events)  # bare-array form, no wrapper
    assert rows[0]["name"] == "dispatch" and rows[0]["count"] == 2
    assert other == {"instant:rollback": 1}
    p = tmp_path / "bare.trace.json"
    p.write_text(json.dumps(events))
    capsys.readouterr()
    assert mod.main([str(p), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["spans"][0]["count"] == 2
    assert doc["spans"][0]["total_ms"] == pytest.approx(2.0)
    assert doc["markers"] == {"instant:rollback": 1}
    with pytest.raises(ValueError):
        mod.summarize({"not": "a trace"})


def test_validate_metrics_cli(tmp_path, capsys):
    from shadow_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.MetricsRegistry()
    reg.counter_set("engine.events_committed", 3)
    good = tmp_path / "good.json"
    reg.dump(str(good))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({**json.loads(good.read_text()),
                               "schema_version": 99}))
    tool = _load_tool("validate_metrics")
    assert tool.main([str(good)]) == 0
    assert tool.main([str(bad)]) == 1
    assert tool.main([str(good), str(bad)]) == 1
    assert tool.main([str(tmp_path / "missing.json")]) == 1
    capsys.readouterr()


def test_sweep_cli_metrics_trace_and_digest_parity(tmp_path, capsys):
    """The sweep CLI path (today only the solo CLI was exercised): a
    3-job sweep through 2 lanes with --metrics-out + --trace-out must
    produce a schema-v5 document whose per-job audit.digest chains equal
    the solo runs', and a trace whose lanes ride their own named tids."""
    from shadow_tpu.fleet.cli import main as sweep_main
    from shadow_tpu.obs import metrics as obs_metrics

    seeds = [5, 6, 7]
    base = _phold_cfg(seed=seeds[0], stop="700 ms")
    sweep_doc = {
        **base,
        "sweep": {"name": "aud", "lanes": 2,
                  "matrix": {"general.seed": seeds}},
    }
    import yaml

    cfg = tmp_path / "sweep.yaml"
    cfg.write_text(yaml.safe_dump(sweep_doc))
    m_out = tmp_path / "fleet.metrics.json"
    t_out = tmp_path / "fleet.trace.json"
    rc = sweep_main([str(cfg), "--metrics-out", str(m_out),
                     "--trace-out", str(t_out)])
    capsys.readouterr()
    assert rc == 0

    doc = json.loads(m_out.read_text())
    obs_metrics.validate_metrics_doc(doc)
    assert_current_metrics_schema(doc)
    rows = doc["fleet"]["jobs"]
    assert len(rows) == 3 and all(r["status"] == "done" for r in rows)
    for row, seed in zip(rows, seeds):
        solo = build_simulation(_phold_cfg(seed=seed, stop="700 ms"))
        solo.run()
        assert row["audit"]["chain"] == solo.audit_chain() != 0, row["name"]

    trace = json.loads(t_out.read_text())
    evs = trace["traceEvents"]
    names = {
        (e["tid"], e["args"]["name"]) for e in evs
        if e.get("ph") == "M" and e["name"] == "thread_name"
    }
    assert (0, "driver") in names
    assert (1, "lane 0") in names and (2, "lane 1") in names
    jobs = [e for e in evs if e.get("ph") == "X" and e.get("cat") == "job"]
    assert len(jobs) == 3  # one residency span per job, on its lane's tid
    assert {e["tid"] for e in jobs} <= {1, 2}
    assert {e["args"]["status"] for e in jobs} == {"done"}
    admits = [e for e in evs if e.get("ph") == "i" and e["name"] == "admit"]
    assert len(admits) == 3 and all(e["tid"] in (1, 2) for e in admits)
    assert any(
        e.get("ph") == "X" and e["name"] == "dispatch" and e["tid"] == 0
        for e in evs
    )
