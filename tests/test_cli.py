"""CLI + config→driver builder tests.

The CLI is the reference's `shadow config.yaml` surface (core/main.c:121);
these tests run it in-process via main(argv). The managed-process plane tests
verify the topology wiring end to end: RTTs observed by REAL processes equal
the GML edge latency exactly on the virtual clock.
"""

import pathlib

import pytest

from shadow_tpu.__main__ import main
from shadow_tpu.procs import build as build_mod

pytestmark = pytest.mark.quick


NS_PER_MS = 1_000_000

PHOLD_YAML = """
general:
  stop_time: 2
  seed: 5
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 512
  events_per_host_per_window: 8
hosts:
  peer:
    quantity: 4
    app_model: phold
    app_options: {msgload: 1, runtime: 1}
"""


def _procs_yaml(apps, lat_ms=30):
    return f"""
general:
  stop_time: 30 s
  seed: 3
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "{lat_ms} ms" packet_loss 0.0 ]
      ]
hosts:
  server:
    processes:
      - path: {apps['udp_echo_server']}
        args: 9000 2
  client:
    processes:
      - path: {apps['udp_echo_client']}
        args: server 9000 2
        start_time: 1 s
"""


def test_show_config(tmp_path, capsys):
    cfg = tmp_path / "c.yaml"
    cfg.write_text(PHOLD_YAML)
    assert main([str(cfg), "--show-config", "--seed", "99"]) == 0
    out = capsys.readouterr().out
    assert "seed: 99" in out
    assert "peer1" in out


def test_bad_config_errors(tmp_path, capsys):
    cfg = tmp_path / "c.yaml"
    cfg.write_text("general: {stop_time: 1}\nnetwork: {graph: {type: gml}}\n"
                   "bogus_section: {}\n")
    assert main([str(cfg)]) == 2
    assert "bogus_section" in capsys.readouterr().err


def test_device_plane_runs(tmp_path, capsys, monkeypatch):
    monkeypatch.chdir(tmp_path)
    cfg = tmp_path / "c.yaml"
    cfg.write_text(PHOLD_YAML)
    assert main([str(cfg)]) == 0
    out = capsys.readouterr().out
    assert "4 hosts" in out
    assert (tmp_path / "shadow.data").is_dir()


def test_existing_data_dir_refused(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "shadow.data").mkdir()
    cfg = tmp_path / "c.yaml"
    cfg.write_text(PHOLD_YAML)
    with pytest.raises(SystemExit, match="already exists"):
        main([str(cfg)])


@pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)
def test_process_plane_e2e(tmp_path, apps, capsys):
    """Full CLI run of the managed-process plane: real binaries, topology
    latency from the GML edge, stdout captured into shadow.data files."""
    cfg = tmp_path / "c.yaml"
    cfg.write_text(_procs_yaml(apps, lat_ms=30))
    data = tmp_path / "data"
    assert main([str(cfg), "--data-directory", str(data)]) == 0
    out = capsys.readouterr().out
    assert "2 processes" in out

    client_out = next((data / "hosts" / "client").glob("*.stdout"))
    lines = client_out.read_text().strip().splitlines()
    rtts = [int(l.split()[1]) for l in lines if l.startswith("rtt")]
    assert len(rtts) == 2
    # virtual clock: RTT is exactly 2 × the GML edge latency
    assert all(r == 2 * 30 * NS_PER_MS for r in rtts), rtts
    server_out = next((data / "hosts" / "server").glob("*.stdout"))
    assert "server done" in server_out.read_text()


@pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)
def test_process_plane_deterministic(tmp_path, apps):
    """determinism1 analog (SURVEY §4): two identical CLI runs produce
    byte-identical per-host stdout files."""
    cfg = tmp_path / "c.yaml"
    cfg.write_text(_procs_yaml(apps, lat_ms=10))

    def run_once(tag):
        data = tmp_path / f"data{tag}"
        assert main([str(cfg), "--data-directory", str(data)]) == 0
        return sorted(
            (p.relative_to(data), p.read_bytes())
            for p in data.rglob("*.stdout")
        )

    assert run_once("a") == run_once("b")


@pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)
def test_failing_process_nonzero_exit(tmp_path, apps, capsys):
    """Plugin-error accounting (manager.c:579-584): a failing managed
    process makes the CLI exit nonzero."""
    cfg = tmp_path / "c.yaml"
    # client with a bad server name resolves nothing and exits nonzero
    cfg.write_text(f"""
general:
  stop_time: 5 s
network:
  graph:
    type: 1_gbit_switch
hosts:
  solo:
    processes:
      - path: {apps['udp_echo_client']}
        args: nosuchhost 9000 1
""")
    data = tmp_path / "data"
    assert main([str(cfg), "--data-directory", str(data)]) == 1
    assert "failed" in capsys.readouterr().err
