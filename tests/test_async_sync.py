"""Asynchronous conservative sync (ISSUE 10): chain-equality regression
matrix across {conservative, optimistic} x {global, islands, fleet},
roughness suppression, lookahead derivation, per-shard gears, and the
reporting tool.

The load-bearing property: the async per-shard-frontier driver
(parallel/islands.make_shard_run_to_async) changes the SCHEDULE — never
the simulation. Every cell of the sync/layout matrix must reproduce the
global conservative engine's audit digest chain bit-for-bit, and the
roughness-suppression bound (cond-mat/0302050) must hold under an
adversarially skewed event load.
"""

import json

import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.core import simtime
from shadow_tpu.parallel import lookahead as lookahead_mod
from shadow_tpu.sim import build_simulation

NEVER = int(simtime.NEVER)


def _decohered_gml(shards, per, seed=7, fast_shard0=False):
    """One vertex per host; decohered intra-shard latencies (no shared
    lattice, so shard windows interleave), large distinct cross-shard
    latencies (generous lookahead). fast_shard0 draws shard 0 from a
    faster band — the deliberately imbalanced load."""
    rng = np.random.RandomState(seed)
    n = shards * per

    def band(a, b):
        if a // per != b // per:
            return 700000, 900000
        if fast_shard0 and a // per == 0:
            return 5000, 60000
        return 30000, 250000

    lines = ["graph ["]
    for v in range(n):
        lines.append(f"  node [ id {v} ]")
    for a in range(n):
        for b in range(a, n):
            lo, hi = band(a, b)
            lines.append(
                f'  edge [ source {a} target {b} latency '
                f'"{int(rng.randint(lo, hi))} us" ]'
            )
    lines.append("]")
    return "\n".join(lines)


def _cfg(shards=2, per=2, stop=6, span=1, seed=11, fast_shard0=False,
         **exp):
    hosts = {}
    for v in range(shards * per):
        hosts[f"h{v:02d}"] = {
            "quantity": 1, "network_node_id": v, "app_model": "phold",
            "app_options": {"msgload": 1, "runtime": stop - 1,
                            "local_span": span},
        }
    experimental = {
        "event_capacity": 1024, "events_per_host_per_window": 8,
        "outbox_slots": 8, "inbox_slots": 4,
    }
    experimental.update(exp)
    return {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "gml", "inline": _decohered_gml(
            shards, per, fast_shard0=fast_shard0)}},
        "experimental": experimental,
        "hosts": hosts,
    }


def _islands_exp(shards=2, **kw):
    d = {"num_shards": shards, "exchange_slots": 16}
    d.update(kw)
    return d


# ---------------------------------------------------------------------------
# the acceptance matrix: every sync x layout cell chains like the global
# conservative engine
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def reference():
    """Global conservative engine: the chain every cell must match."""
    sim = build_simulation(_cfg())
    sim.run(windows_per_dispatch=512)
    return sim.audit_chain(), sim.counters()["events_committed"]


def test_global_optimistic_matches(reference):
    chain, ev = reference
    sim = build_simulation(_cfg())
    sim.run_optimistic()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == ev


def test_islands_barrier_matches(reference):
    chain, ev = reference
    sim = build_simulation(_cfg(**_islands_exp(async_islands=False)))
    assert sim._async is False
    sim.run(windows_per_dispatch=512)
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == ev


def test_islands_async_matches(reference):
    chain, ev = reference
    sim = build_simulation(_cfg(**_islands_exp()))
    assert sim._async is True  # async is the default islands driver
    sim.run(windows_per_dispatch=512)
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == ev
    stats = sim.async_stats()
    assert stats["supersteps"] > 0
    assert stats["shard_windows"] > 0


def test_islands_optimistic_matches(reference):
    chain, ev = reference
    sim = build_simulation(_cfg(**_islands_exp()))
    sim.run_optimistic()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == ev


def _fleet(async_on, optimistic=False):
    from shadow_tpu.fleet import JobSpec, build_fleet

    cfg = _cfg(**_islands_exp(async_islands=async_on))
    jobs = [JobSpec("a", cfg), JobSpec("b", dict(cfg))]
    fleet = build_fleet(jobs)
    if optimistic:
        fleet.run_optimistic()
    else:
        fleet.run()
    assert fleet.ok()
    return fleet


def test_fleet_barrier_matches(reference):
    chain, ev = reference
    fleet = _fleet(async_on=False)
    for row in fleet.results():
        assert row["audit"]["chain"] == chain, row["name"]
        assert row["events_committed"] == ev


def test_fleet_async_matches(reference):
    chain, ev = reference
    fleet = _fleet(async_on=True)
    assert fleet._async
    for row in fleet.results():
        assert row["audit"]["chain"] == chain, row["name"]
        assert row["events_committed"] == ev
    assert fleet.async_stats()["supersteps"] > 0
    # both axes of asynchrony: per-lane frontier matrix rode back
    assert fleet._async_frontier is not None
    assert fleet._async_frontier.shape == (fleet.lanes, 2)


def test_fleet_optimistic_matches(reference):
    chain, ev = reference
    fleet = _fleet(async_on=True, optimistic=True)
    for row in fleet.results():
        assert row["audit"]["chain"] == chain, row["name"]
        assert row["events_committed"] == ev


def test_fleet_refuses_mixed_sync_modes():
    """async_islands is a kernel-shaping field: the sweep validator
    rejects a mixed fleet up front (and FleetSimulation._check_compat
    backstops direct construction)."""
    from shadow_tpu.fleet import JobSpec, build_fleet
    from shadow_tpu.fleet.sweep import SweepError

    a = _cfg(**_islands_exp(async_islands=True))
    b = _cfg(**_islands_exp(async_islands=False))
    with pytest.raises(SweepError, match="async_islands"):
        build_fleet([JobSpec("a", a), JobSpec("b", b)])


# ---------------------------------------------------------------------------
# roughness suppression (cond-mat/0302050)
# ---------------------------------------------------------------------------


def test_roughness_spread_stays_bounded_under_skew():
    """Adversarially skewed load: shard 0 runs a much faster event
    timescale, so the other shards would sprint arbitrarily far ahead of
    it under pure lookahead slack. With a tight spread bound they must
    yield instead, the observed frontier spread must stay within
    bound + one window width, and the chain must still be bit-identical
    to the barrier run (yields change the schedule, never the sim)."""
    base = _cfg(shards=2, per=2, stop=8, fast_shard0=True,
                **_islands_exp(async_islands=False))
    barrier = build_simulation(base)
    barrier.run(windows_per_dispatch=512)

    spread = 150_000_000  # 150 ms: far below the ~800 ms lookahead slack
    tight = build_simulation(_cfg(
        shards=2, per=2, stop=8, fast_shard0=True,
        **_islands_exp(async_spread=spread),
    ))
    assert int(tight._async_spread) == spread
    tight.run(windows_per_dispatch=512)

    assert tight.audit_chain() == barrier.audit_chain()
    stats = tight.async_stats()
    assert stats["yields"] > 0, "suppression never engaged"
    width = int(np.max(np.asarray(tight._async_runahead)))
    gauges = tight.async_gauges()
    assert gauges["frontier_spread_max_ns"] <= spread + width, (
        gauges["frontier_spread_max_ns"], spread, width
    )


def test_initial_frontier_clamped_by_deferred_exchange():
    """The per-dispatch initial frontier f0 must min against the gathered
    exch_deferred_min, exactly like the in-loop horizon: an in-transit
    deferred row has already paid its path latency and lands at its pool
    time, so deriving f0 from min_j(mn_j + L[j->i]) alone charges the
    link a second time and can initialize a frontier PAST the landing
    time — frontier is a monotone max in the carry, so once the row
    lands the destination emits below its advertised bound and a
    neighbor's committed window can swallow the arrival (silent: the
    conservative loop has no arrival check). max_windows=0 skips the
    loop body, so the returned frontier IS f0; a pending deferred row
    below every pool event must clamp every shard's f0 to it."""
    import jax.numpy as jnp

    sim = build_simulation(_cfg(**_islands_exp()))
    assert sim._async is True
    mn0 = int(np.asarray(sim.state.pool.time).min())
    t_d = mn0 - 50_000  # in-transit row earlier than all pool events
    state = sim.state.replace(
        exch_deferred_min=jnp.asarray(
            [t_d] + [NEVER] * (sim.num_shards - 1), jnp.int64
        )
    )
    out = sim._run_to_async(
        state, sim.params, sim._async_runahead, sim._async_look_in,
        sim._async_spread, sim.stop_time, 0,
    )
    frontier = np.asarray(out[5]).reshape(-1)
    assert (frontier == t_d).all(), frontier


def test_deferred_exchange_across_dispatch_boundary(reference):
    """Integration arm of the f0-clamp regression: exchange_slots=1 plus
    tiny dispatches force deferred rows to be in flight across many
    dispatch boundaries (each re-deriving f0 from pool state); the run
    must stay bit-identical to the barrier schedule and must actually
    have deferred."""
    chain, ev = reference
    sim = build_simulation(_cfg(**_islands_exp(exchange_slots=1)))
    assert sim._async is True
    sim.run(windows_per_dispatch=2)
    assert sim.counters()["exchange_deferred"] > 0, (
        "workload never deferred — the regression path was not exercised"
    )
    assert sim.counters()["events_committed"] == ev
    assert sim.audit_chain() == chain


def test_loose_spread_runs_further_ahead():
    """Control arm: the auto (loose) bound lets the fast shards spread
    beyond the tight bound — proving the tight run's flat frontier
    surface came from suppression, not from the workload."""
    loose = build_simulation(_cfg(
        shards=2, per=2, stop=8, fast_shard0=True, **_islands_exp(),
    ))
    loose.run(windows_per_dispatch=512)
    g = loose.async_gauges()
    assert loose.async_stats()["yields"] == 0
    assert g["frontier_spread_max_ns"] > 150_000_000 + int(
        np.max(np.asarray(loose._async_runahead))
    )


# ---------------------------------------------------------------------------
# lookahead derivation (parallel/lookahead.py)
# ---------------------------------------------------------------------------


def test_lookahead_derive_block_partition():
    # 4 hosts on 4 vertices, 2 shards: lookahead = min over the cross
    # block; diagonal = intra minimum; unreachable pairs unconstrained
    lat = np.full((4, 4), NEVER, np.int64)
    lat[0, 1] = lat[1, 0] = 10
    lat[2, 3] = lat[3, 2] = 20
    lat[0, 2] = 100
    lat[1, 3] = 70
    spec = lookahead_mod.derive(lat, np.arange(4), 2)
    assert spec.matrix[0, 0] == 10 and spec.matrix[1, 1] == 20
    assert spec.matrix[0, 1] == 70  # min(lat[0,2]=100, lat[1,3]=70)
    assert spec.matrix[1, 0] == NEVER  # no back edges: unconstrained
    assert spec.min_cross == 70 and spec.critical == (0, 1)
    ie = lookahead_mod.in_edge_matrix(spec)
    assert ie[0, 0] == NEVER and ie[1, 1] == NEVER  # self never binds
    assert ie[1, 0] == 70  # shard 1's in-edge from shard 0


def test_lookahead_assignment_permutation():
    # rebalance moves host 1 into shard 1: the intra/cross minima follow
    lat = np.array([[5, 10], [10, 5]], np.int64)
    hv = np.array([0, 0, 1, 1])
    block = lookahead_mod.derive(lat, hv, 2)
    assert block.matrix[0, 0] == 5 and block.matrix[0, 1] == 10
    mixed = lookahead_mod.derive(
        lat, hv, 2, assignment=np.array([0, 2, 1, 3])
    )
    # each shard now holds one host of each vertex: every pair sees the
    # full matrix minimum
    assert mixed.matrix[0, 0] == 5 and mixed.matrix[0, 1] == 5


def test_shard_runahead_floor_and_cap():
    lat = np.full((2, 2), NEVER, np.int64)
    lat[0, 1] = lat[1, 0] = 50
    spec = lookahead_mod.derive(lat, np.array([0, 0, 1, 1]), 2)
    # intra NEVER (no intra path): width clamps to the sort-key cap,
    # never overflows; the floor is the configured runahead
    w = lookahead_mod.shard_runahead(spec, 50)
    assert (w == lookahead_mod.WIDTH_CAP).all()
    lat[0, 0] = lat[1, 1] = 7
    spec = lookahead_mod.derive(lat, np.array([0, 0, 1, 1]), 2)
    assert (lookahead_mod.shard_runahead(spec, 30) == 30).all()  # floor
    assert (lookahead_mod.shard_runahead(spec, 3) == 7).all()  # intra


def test_derived_lookahead_in_runahead_error_hint():
    sim = build_simulation(_cfg(**_islands_exp()))
    hint = sim._runahead_bound_hint()
    assert "cross-shard path latency" in hint
    assert "experimental.runahead" in hint


# ---------------------------------------------------------------------------
# per-shard gears (gearbox.ShardGearShifter)
# ---------------------------------------------------------------------------


def test_shard_gear_shifter_envelope():
    from shadow_tpu.core.gearbox import GearSpec, ShardGearShifter

    ladder = [
        GearSpec(0, 256, 8, hi=200, fill=150, up=175),
        GearSpec(1, 512, 8, hi=400, fill=300, up=350),
    ]
    sh = ShardGearShifter(ladder, 2, down_after=2)
    sh.seed(0)
    # one hot shard raises the envelope immediately
    assert sh.observe(0, [10, 180]) == 1
    sh.seed(1)
    # a burst on shard 1 must NOT reset shard 0's downshift streak
    assert sh.observe(1, [10, 300]) is None
    assert sh.observe(1, [10, 300]) is None
    # shard 0's level dropped after its own streak, but the envelope
    # stays up while shard 1 still needs the big gear
    assert sh.levels[0] == 0 and sh.levels[1] == 1
    # shard 1 cools: after ITS streak the envelope finally drops
    assert sh.observe(1, [10, 10]) is None
    assert sh.observe(1, [10, 10]) == 0


def test_shard_gear_press_forces_envelope_up():
    from shadow_tpu.core.gearbox import GearSpec, ShardGearShifter

    ladder = [
        GearSpec(0, 256, 8, hi=200, fill=150, up=175),
        GearSpec(1, 512, 8, hi=400, fill=300, up=350),
    ]
    sh = ShardGearShifter(ladder, 2)
    sh.seed(0)
    assert sh.observe(0, [10, 10], press=[False, True]) == 1


def test_shifter_initiated_shift_keeps_per_shard_levels():
    """_shift_gear must not re-seed the shard shifter for envelope
    changes the shifter itself produced (level == max(levels)): seeding
    hoists every cool shard to the envelope and clears its downshift
    streak, reverting to fleet-wide gearing at each shift boundary.
    External shifts (pressure downshift, scalar path, restore) still
    re-align."""
    sim = build_simulation(_cfg(**_islands_exp(pool_gears=2)))
    sh = sim._shard_shifter
    assert sh is not None
    low = sim._gear_ladder[0].level
    top = sim._gear_ladder[-1].level
    sh.levels = [low, top]
    sh._streak = [1, 0]
    sim._shift_gear(top)  # shifter-initiated: envelope == max(levels)
    assert sh.levels == [low, top]
    assert sh._streak == [1, 0]
    sim._shift_gear(low)  # external downshift below the envelope
    assert sh.levels == [low, low]
    assert sh._streak == [0, 0]


# ---------------------------------------------------------------------------
# telemetry + checkpoint carry
# ---------------------------------------------------------------------------


def test_async_metrics_schema_v9(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics

    sim = build_simulation(_cfg(**_islands_exp()))
    sim.run(windows_per_dispatch=512)
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(str(tmp_path / "m.json"))
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    assert_current_metrics_schema(doc)
    assert doc["counters"]["async.supersteps"] > 0
    assert doc["counters"]["async.shard_windows"] > 0
    assert "async.frontier_spread_max_ns" in doc["gauges"]
    assert "async.spread_bound_ns" in doc["gauges"]
    # negative async counters are rejected (monotonic tallies)
    bad = json.loads(json.dumps(doc))
    bad["counters"]["async.supersteps"] = -1
    with pytest.raises(ValueError, match="async counter"):
        obs_metrics.validate_metrics_doc(bad)


def test_barrier_run_emits_no_async_keys(tmp_path):
    from shadow_tpu.obs import metrics as obs_metrics

    sim = build_simulation(_cfg(**_islands_exp(async_islands=False)))
    sim.run(windows_per_dispatch=512)
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(str(tmp_path / "m.json"))
    assert not any(k.startswith("async.") for k in doc["counters"])
    assert not any(k.startswith("async.") for k in doc["gauges"])


def test_checkpoint_header_carries_async_block(tmp_path):
    from shadow_tpu.core import checkpoint as ckpt_mod

    sim = build_simulation(_cfg(**_islands_exp()))
    sim.run(until=3 * simtime.NS_PER_SEC, windows_per_dispatch=512)
    now = int(np.max(np.asarray(sim.state.now)))
    path, _ = ckpt_mod.save_ring(sim, str(tmp_path), seq=0, sim_ns=now)
    meta = ckpt_mod.load_meta(path)
    a = meta["async"]
    assert a["spread_ns"] == int(sim._async_spread)
    assert len(a["runahead_ns"]) == sim.num_shards
    assert "min_cross_lookahead_ns" in a
    assert len(a["frontier_ns"]) == sim.num_shards
    # resume reproduces the uninterrupted chain (frontiers re-derive
    # from pool state — the restart-safety property)
    res = build_simulation(_cfg(**_islands_exp()))
    res.resume_from(str(tmp_path))
    res.run(windows_per_dispatch=512)
    full = build_simulation(_cfg(**_islands_exp()))
    full.run(windows_per_dispatch=512)
    assert res.audit_chain() == full.audit_chain()


# ---------------------------------------------------------------------------
# tools/lookahead_report.py
# ---------------------------------------------------------------------------


def test_lookahead_report_tool(tmp_path, capsys):
    import yaml

    from tools import lookahead_report

    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(_cfg(**_islands_exp())))
    assert lookahead_report.main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "critical link" in out and "lookahead matrix" in out
    assert lookahead_report.main([str(p), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["num_shards"] == 2
    assert doc["min_cross_ns"] is not None
    assert len(doc["matrix_ns"]) == 2
    assert doc["critical_link"] is not None
    # bad inputs exit 2 with a one-line diagnosis, never a traceback
    assert lookahead_report.main([str(tmp_path / "missing.yaml")]) == 2
    assert lookahead_report.main([str(p), "--shards", "0"]) == 2
