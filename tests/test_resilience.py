"""Survivable execution (ISSUE 6): backend-loss supervision, drain-to-
checkpoint, and audit-verified resume.

The acceptance gate: a `kill_backend` injection mid-run drains to a
crash-consistent checkpoint, and the resumed (or CPU-failover) run's
final audit digest chain is BIT-IDENTICAL to an uninterrupted run —
across {conservative, optimistic} × {global, islands, fleet}. The chain
(obs/audit.py, PR 5) is the proof instrument: recovery that merely
"looks right" cannot pass it.

Supervisors here inject a no-op sleep and tiny probe budgets: wall-clock
scheduling is the only thing perturbed — simulation results never depend
on it, which is exactly the property under test.
"""

import json
import os
import sys

import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.core.supervisor import (
    BACKEND_LOST,
    BackendLost,
    BackendSupervisor,
    FATAL,
    RESOURCE_EXHAUSTED,
    TRANSIENT,
    classify_failure,
)
from shadow_tpu.faults import plan as plan_mod
from shadow_tpu.obs import audit as audit_mod
from shadow_tpu.sim import build_simulation

pytestmark = pytest.mark.quick

DEVICE_YAML = """
general:
  stop_time: 4
  seed: 13
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "81920 Kibit" bandwidth_up "81920 Kibit" ]
        edge [ source 0 target 0 latency "50 ms" packet_loss 0.0 ]
      ]
experimental:
  event_capacity: 1024
  events_per_host_per_window: 8
hosts:
  peer:
    quantity: 8
    app_model: phold
    app_options: {msgload: 1, runtime: 3}
"""

ISLANDS_YAML = DEVICE_YAML.replace(
    "  event_capacity: 1024",
    "  event_capacity: 1024\n  num_shards: 2",
)


def _build(yaml):
    return build_simulation(yaml)


def _run(sim, sync):
    if sync == "optimistic":
        sim.run_optimistic()
    else:
        sim.run()
    return sim


def _quiet_supervisor(policy, **kw):
    """A supervisor whose waits are instantaneous: wall scheduling only —
    never simulation results."""
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("probe_budget_s", 30.0)
    return BackendSupervisor(policy, **kw)


_BASELINES: dict = {}


def _baseline(yaml, sync):
    """One uninterrupted run per (layout, sync): (chain, events)."""
    key = (yaml, sync)
    if key not in _BASELINES:
        sim = _run(_build(yaml), sync)
        _BASELINES[key] = (
            sim.audit_chain(), sim.counters()["events_committed"],
        )
        assert _BASELINES[key][0] != 0
    return _BASELINES[key]


# ---------------------------------------------------------------------------
# classification + supervisor unit behavior (pure host code)
# ---------------------------------------------------------------------------


def test_classify_failure():
    assert classify_failure(RuntimeError("UNAVAILABLE: socket closed")) \
        == BACKEND_LOST
    assert classify_failure(RuntimeError("connection reset by peer")) \
        == BACKEND_LOST
    assert classify_failure(BackendLost("x")) == BACKEND_LOST
    # schema-v8 pressure plane: XLA OOM is its own class now — the
    # degradation ladder handles it, not a blind retry (PR 9)
    assert classify_failure(RuntimeError("RESOURCE_EXHAUSTED: hbm")) \
        == RESOURCE_EXHAUSTED
    assert classify_failure(RuntimeError("ABORTED: collective")) \
        == TRANSIENT
    assert classify_failure(ValueError("shape mismatch")) == FATAL
    assert classify_failure(RuntimeError("speculation violation")) == FATAL


@pytest.mark.parametrize("msg", [
    # mesh-collective runtime failures: ONE participant chip died.
    # These must classify BACKEND_LOST (drain + policy) — never
    # TRANSIENT (a bounded retry against a dead ppermute peer spins
    # until the retry budget burns) and never FATAL (it is an
    # infrastructure failure, not a bug) — even when the runtime
    # phrases them with a transient-sounding prefix.
    "ABORTED: ppermute participant failed on device 3",
    "INTERNAL: collective-permute peer unreachable",
    "ABORTED: all-reduce timed out waiting for peer",
    "all_gather failed: remote device lost contact",
    "collective operation aborted: participant failed",
    "NCCL error: peer failure detected",
    "ICI link down between chips 2 and 3",
])
def test_mesh_collective_failures_classify_chip_scoped(msg):
    """ISSUE 13 satellite: the chip-scoped marker table, mirroring the
    backend-lost marker rows above — BACKEND_LOST and chip-scoped."""
    from shadow_tpu.core.supervisor import chip_scoped

    exc = RuntimeError(msg)
    assert classify_failure(exc) == BACKEND_LOST
    assert chip_scoped(exc)


def test_generic_transient_stays_transient():
    """The chip table must not swallow the generic retry class: a bare
    'ABORTED: collective' (no op-scoped marker) keeps its bounded
    retry, and plain transients are untouched."""
    from shadow_tpu.core.supervisor import chip_scoped

    assert classify_failure(RuntimeError("ABORTED: collective")) \
        == TRANSIENT
    assert classify_failure(RuntimeError("try again later")) == TRANSIENT
    assert not chip_scoped(RuntimeError("try again later"))


def test_supervisor_transient_retry_then_success():
    sup = _quiet_supervisor("abort")
    calls = {"n": 0}

    def thunk():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("ABORTED: collective interrupted")
        return "ok"

    assert sup.call("step", thunk) == "ok"
    assert calls["n"] == 3
    assert sup.counters["retries"] == 2
    assert sup.counters["backoffs"] == 2


def test_supervisor_transient_exhaustion_escalates_to_loss():
    sup = _quiet_supervisor("abort", max_retries=1)

    class Sim:
        def _drain_to_checkpoint(self, reason, ckpt_dir=None):
            return None

    sup.bind(Sim())
    with pytest.raises(BackendLost):
        sup.call("step", lambda: (_ for _ in ()).throw(
            RuntimeError("ABORTED: again and again")
        ))
    assert sup.counters["backend_losses"] == 1
    assert sup.counters["drains"] == 1


def test_supervisor_fatal_propagates_unchanged():
    sup = _quiet_supervisor("wait")
    with pytest.raises(ValueError, match="real bug"):
        sup.call("step", lambda: (_ for _ in ()).throw(
            ValueError("real bug")
        ))
    assert sup.counters["drains"] == 0


def test_plan_backend_ops_validate():
    good = {
        "kind": plan_mod.PLAN_KIND,
        "schema_version": plan_mod.PLAN_SCHEMA_VERSION,
        "faults": [
            {"at": "1 s", "op": "kill_backend"},
            {"at": "1 s", "op": "kill_backend", "recover_after": 2},
            {"at": "2 s", "op": "stall_backend", "count": 3},
        ],
    }
    plan_mod.validate_fault_plan_doc(good)
    faults = plan_mod.parse_fault_plan(good["faults"])
    assert faults[1].recover_after == 2
    assert all(f.op in plan_mod.BACKEND_OPS for f in faults)
    for bad in (
        [{"at": 1, "op": "kill_backend", "recover_after": -1}],
        [{"at": 1, "op": "kill_backend", "host": 3}],
        [{"at": 1, "op": "stall_backend", "count": 0}],
    ):
        with pytest.raises(plan_mod.FaultPlanError):
            plan_mod.parse_fault_plan(bad)


# ---------------------------------------------------------------------------
# chaos matrix: kill_backend mid-run, drain → resume, across
# {conservative, optimistic} × {global, islands}; fleet below
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("sync", ["conservative", "optimistic"])
@pytest.mark.parametrize(
    "yaml", [DEVICE_YAML, ISLANDS_YAML], ids=["global", "islands"]
)
def test_kill_backend_drain_resume_chain_identical(yaml, sync, tmp_path):
    """Acceptance gate: drain at the injected loss, resume from the drain
    checkpoint, finish — the final digest chain and committed-event total
    are bit-identical to the uninterrupted run's."""
    chain, events = _baseline(yaml, sync)

    sim = _build(yaml)
    sim.checkpoint_dir = str(tmp_path)
    sim.attach_supervisor(_quiet_supervisor("abort"))
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend"}]  # stays down: abort path
    ))
    with pytest.raises(BackendLost, match="drained to"):
        _run(sim, sync)
    entries = [n for n in os.listdir(tmp_path) if n.startswith("drain-")]
    assert len(entries) == 1
    # drain metadata rides the checkpoint header (core/checkpoint.py)
    from shadow_tpu.core import checkpoint as ckpt_mod

    meta = ckpt_mod.load_meta(str(tmp_path / entries[0]))
    assert meta["drain"]["reason"].startswith("backend_lost:")
    assert meta["drain"]["policy"] == "abort"
    assert "chain" in meta["audit"]

    resumed = _build(yaml)
    info = resumed.resume_from(str(tmp_path))
    assert info["fallbacks"] == 0
    _run(resumed, sync)
    assert resumed.audit_chain() == chain
    assert resumed.counters()["events_committed"] == events


@pytest.mark.parametrize(
    "yaml", [DEVICE_YAML, ISLANDS_YAML], ids=["global", "islands"]
)
def test_kill_backend_cpu_failover_chain_identical(yaml):
    """--on-backend-loss cpu: the run completes in-process on the CPU
    backend with the exact uninterrupted chain; the supervisor records
    the failover (and the failback once the primary answers again)."""
    chain, events = _baseline(yaml, "conservative")
    sim = _build(yaml)
    sup = _quiet_supervisor("cpu", recheck_every=1)
    sim.attach_supervisor(sup)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend", "recover_after": 1}]
    ))
    # short dispatches: several post-failover rechecks, so the primary's
    # simulated recovery (second probe) triggers the upshift back
    sim.run(windows_per_dispatch=4)
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert sup.counters["drains"] == 1
    assert sup.counters["failovers"] == 1
    assert sup.counters["failbacks"] == 1
    assert not sup.failover  # ended back on the primary


def test_kill_backend_wait_hot_resume():
    """--on-backend-loss wait: re-probe until the simulated backend
    answers, rebind kernels, continue — nothing lost, chain identical."""
    chain, events = _baseline(DEVICE_YAML, "conservative")
    sim = _build(DEVICE_YAML)
    sup = _quiet_supervisor("wait")
    sim.attach_supervisor(sup)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend", "recover_after": 3}]
    ))
    sim.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert sup.counters["hot_resumes"] == 1
    assert sup.counters["probes"] >= 3
    assert sup.counters["downtime_ns"] >= 0


def test_wait_budget_exhaustion_still_drains(tmp_path):
    """A backend that never returns exhausts the probe budget: the run
    dies with BackendLost, but the drain checkpoint is already on disk."""
    sim = _build(DEVICE_YAML)
    sim.checkpoint_dir = str(tmp_path)
    sup = _quiet_supervisor("wait", probe_budget_s=0.0)
    sim.attach_supervisor(sup)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend"}]
    ))
    with pytest.raises(BackendLost, match="probe budget"):
        sim.run()
    assert any(n.startswith("drain-") for n in os.listdir(tmp_path))


def test_stall_backend_escalation_ladder():
    """stall_backend: consecutive deadline misses escalate to a probe
    (the bounded-lag signal); a healthy probe keeps the run going and the
    result is untouched."""
    chain, events = _baseline(DEVICE_YAML, "conservative")
    sim = _build(DEVICE_YAML)
    sup = _quiet_supervisor("wait", stall_limit=2)
    sim.attach_supervisor(sup)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "stall_backend", "count": 2}]
    ))
    sim.run(windows_per_dispatch=4)
    assert sim.audit_chain() == chain
    assert sup.counters["stalls"] == 2
    assert sup.counters["probes"] >= 1
    assert sup.counters["drains"] == 0  # healthy probe: no escalation


def test_resume_skips_already_fired_backend_faults(tmp_path):
    """Re-attaching the SAME fault plan on resume must not re-drain: the
    outage at/before the restored frontier already happened — it is the
    reason the run is resuming."""
    chain, events = _baseline(DEVICE_YAML, "conservative")
    sim = _build(DEVICE_YAML)
    sim.checkpoint_dir = str(tmp_path)
    sim.attach_supervisor(_quiet_supervisor("abort"))
    plan = [{"at": "1 s", "op": "kill_backend"}]
    sim.attach_faults(plan_mod.parse_fault_plan(plan))
    with pytest.raises(BackendLost):
        sim.run()

    resumed = _build(DEVICE_YAML)
    resumed.attach_faults(plan_mod.parse_fault_plan(plan))  # re-attached
    resumed.attach_supervisor(_quiet_supervisor("abort"))
    resumed.resume_from(str(tmp_path))
    assert resumed.fault_injector.pending == 0  # marked fired on resume
    resumed.run()
    assert resumed.audit_chain() == chain
    assert resumed.counters()["events_committed"] == events


def test_digest_doc_diff_confirms_resume_parity(tmp_path):
    """The divergence bisector view of the gate: digest DOCUMENTS from an
    uninterrupted run and a drained+resumed run end on the same final
    chain and per-host sub-chains (frontier-aligned diff, the engine
    behind tools/diff_digest.py)."""
    ref = _build(DEVICE_YAML)
    ref.attach_audit(meta={"arm": "ref"})
    ref.run()
    doc_ref = ref.write_digest(str(tmp_path / "ref.json"))

    sim = _build(DEVICE_YAML)
    sim.checkpoint_dir = str(tmp_path / "ck")
    sim.attach_supervisor(_quiet_supervisor("abort"))
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend"}]
    ))
    with pytest.raises(BackendLost):
        sim.run()
    resumed = _build(DEVICE_YAML)
    resumed.attach_audit(meta={"arm": "resumed"})
    resumed.resume_from(str(tmp_path / "ck"))
    resumed.run()
    doc_res = resumed.write_digest(str(tmp_path / "resumed.json"))

    rep = audit_mod.diff_digest_docs(doc_ref, doc_res)
    assert rep["final_chain_equal"]
    assert rep["divergent_hosts"] == []
    assert rep["first_divergent_record"] is None


# ---------------------------------------------------------------------------
# fleet: whole-sweep drain, admission pause, requeue, resume; lane reclaim
# ---------------------------------------------------------------------------


def _job_cfg(seed, stop_s, quantity=8):
    return {
        "general": {"stop_time": f"{stop_s} s", "seed": seed},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "81920 Kibit" '
            'bandwidth_up "81920 Kibit" ]\n'
            '  edge [ source 0 target 0 latency "50 ms" '
            'packet_loss 0.0 ]\n'
            ']\n')}},
        "experimental": {"event_capacity": 512,
                         "events_per_host_per_window": 8,
                         "outbox_slots": 8, "inbox_slots": 4},
        "hosts": {"peer": {"quantity": quantity, "app_model": "phold",
                           "app_options": {"msgload": 1, "runtime": 1}}},
    }


@pytest.fixture(scope="module")
def fleet_cfgs():
    return [_job_cfg(100 + i, 2 + i) for i in range(3)]


@pytest.fixture(scope="module")
def fleet_solo_chains(fleet_cfgs):
    chains = []
    for c in fleet_cfgs:
        s = build_simulation(c)
        s.run()
        chains.append(s.audit_chain())
    return chains


@pytest.mark.parametrize("sync", ["conservative", "optimistic"])
def test_fleet_kill_backend_drain_and_resume(
    fleet_cfgs, fleet_solo_chains, sync, tmp_path
):
    """Fleet acceptance leg: kill_backend mid-sweep drains every running
    lane's slice + a drain-annotated manifest, pauses admission, requeues
    the in-flight jobs, and `resume_fleet` finishes the sweep with every
    job's chain equal to its solo run."""
    from shadow_tpu.fleet import JobSpec, build_fleet, resume_fleet

    fleet = build_fleet(
        [JobSpec(name=f"j{i}", config=fleet_cfgs[i]) for i in range(3)],
        lanes=2, checkpoint_dir=str(tmp_path),
    )
    fleet.attach_supervisor(_quiet_supervisor("abort"))
    fleet.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend"}]
    ))
    with pytest.raises(BackendLost):
        if sync == "optimistic":
            fleet.run_optimistic()
        else:
            fleet.run()
    # drain truth: admission paused, in-flight lanes requeued in-memory,
    # manifest carries the drain reason with the slices still RUNNING
    assert fleet._admission_paused
    assert fleet.sched.jobs_requeued >= 1
    assert all(r.status == "queued" for r in fleet.sched.records)
    man = json.load(open(tmp_path / "manifest.json"))
    assert man["drain"]["reason"].startswith("backend_lost:")
    running = [e for e in man["jobs"] if e["status"] == "running"]
    assert running and all("file" in e for e in running)

    resumed = resume_fleet(str(tmp_path))
    if sync == "optimistic":
        resumed.run_optimistic()
    else:
        resumed.run()
    assert resumed.ok()
    by_name = {r.name: r.audit.get("chain") for r in resumed.sched.records}
    for i in range(3):
        assert by_name[f"j{i}"] == fleet_solo_chains[i], f"j{i}"


def test_fleet_kill_backend_wait_recovers_in_process(
    fleet_cfgs, fleet_solo_chains
):
    """Fleet + policy wait: the sweep survives the outage in-process —
    admission resumes after recovery and every chain matches solo."""
    from shadow_tpu.fleet import JobSpec, build_fleet

    fleet = build_fleet(
        [JobSpec(name=f"j{i}", config=fleet_cfgs[i]) for i in range(3)],
        lanes=2,
    )
    sup = _quiet_supervisor("wait")
    fleet.attach_supervisor(sup)
    fleet.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend", "recover_after": 2}]
    ))
    fleet.run()
    assert fleet.ok()
    assert not fleet._admission_paused
    assert sup.counters["drains"] == 1
    assert sup.counters["hot_resumes"] == 1
    by_name = {r.name: r.audit.get("chain") for r in fleet.sched.records}
    for i in range(3):
        assert by_name[f"j{i}"] == fleet_solo_chains[i], f"j{i}"


def test_fleet_deadline_kill_reclaims_lane_immediately(fleet_cfgs):
    """Satellite gate: a job killed at its wall-clock deadline frees its
    lane for the admission queue in the same pass (lane_reclaims), and
    the queued job still completes."""
    from shadow_tpu.fleet import JobSpec, build_fleet
    from shadow_tpu.obs import metrics as obs_metrics

    jobs = [
        JobSpec(name="doomed", config=_job_cfg(7, 30), deadline_s=1e-6),
        JobSpec(name="healthy", config=_job_cfg(8, 2)),
    ]
    fleet = build_fleet(jobs, lanes=1)
    fleet.run()
    rec = {r.name: r for r in fleet.sched.records}
    assert rec["doomed"].status == "timeout"
    assert rec["healthy"].status == "done"
    assert fleet.sched.lane_reclaims >= 1
    # resilience.lane_reclaims rides the fleet metrics doc (schema v6)
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_fleet(fleet, reg)
    doc = reg.to_doc()
    obs_metrics.validate_metrics_doc(doc)
    assert doc["counters"]["resilience.lane_reclaims"] >= 1


def test_metrics_schema_v6_resilience_namespace():
    """snapshot_device emits the resilience.* namespace from the attached
    supervisor, and the v6 validator accepts it (and rejects negatives)."""
    from shadow_tpu.obs import metrics as obs_metrics

    sim = _build(DEVICE_YAML)
    sup = _quiet_supervisor("cpu", recheck_every=1)
    sim.attach_supervisor(sup)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend", "recover_after": 1}]
    ))
    sim.run()
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_device(sim, reg)
    doc = reg.to_doc()
    assert_current_metrics_schema(doc)
    obs_metrics.validate_metrics_doc(doc)
    assert doc["counters"]["resilience.drains"] == 1
    assert doc["counters"]["resilience.failovers"] == 1
    bad = dict(doc)
    bad["counters"] = {**doc["counters"], "resilience.drains": -1}
    with pytest.raises(ValueError, match="resilience"):
        obs_metrics.validate_metrics_doc(bad)


# ---------------------------------------------------------------------------
# bench.py probe-budget accounting (satellite): the r05 overrun class
# ---------------------------------------------------------------------------


class _FakeTime:
    """Deterministic clock: probes and sleeps advance it; no real waits."""

    def __init__(self):
        self.now = 0.0

    def monotonic(self):
        return self.now

    def sleep(self, s):
        self.now += s


def _import_bench():
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def test_bench_probe_timeout_clamped_to_budget(monkeypatch):
    """r05: probe 6 launched with 84 s of budget and overran to −166 s.
    Every probe's subprocess timeout must be clamped to the remaining
    budget, exhaustion must return False promptly, and the timeline must
    carry ok:false entries."""
    import subprocess as sp

    bench = _import_bench()
    fake = _FakeTime()
    monkeypatch.setattr(bench.time, "monotonic", fake.monotonic)
    monkeypatch.setattr(bench.time, "sleep", fake.sleep)
    seen = []

    def fake_run(argv, timeout=None, **kw):
        seen.append((fake.now, timeout))
        fake.now += timeout  # the probe hangs for its full timeout
        raise sp.TimeoutExpired(argv, timeout)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    ok = bench.wait_for_backend(max_wait_s=300.0, probe_timeout_s=240.0)
    assert ok is False
    assert len(seen) >= 2, "expected a clamped follow-up probe"
    budget_end = 300.0
    for started, timeout in seen:
        remaining = budget_end - started
        assert timeout <= max(5.0, remaining) + 1e-9, (started, timeout)
    # the final probe was clamped below the full 240 s
    assert seen[-1][1] < 240.0
    # and the clock never overran the budget by a probe width
    assert fake.now <= budget_end + 5.0
    assert all(not e["ok"] for e in bench._PROBE_LOG)
    assert all("timeout_s" in e for e in bench._PROBE_LOG)


def test_bench_probe_backoff_is_jittered_exponential(monkeypatch):
    """Sleeps between probes grow (exponential base) and are jittered —
    never a fixed interval."""
    import subprocess as sp

    bench = _import_bench()
    fake = _FakeTime()
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        fake.now += s

    monkeypatch.setattr(bench.time, "monotonic", fake.monotonic)
    monkeypatch.setattr(bench.time, "sleep", sleep)

    def fake_run(argv, timeout=None, **kw):
        fake.now += 1.0  # fast-failing probe
        raise sp.TimeoutExpired(argv, timeout)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.random, "random", lambda: 0.5)
    bench.wait_for_backend(max_wait_s=120.0, probe_timeout_s=240.0)
    assert len(sleeps) >= 3
    # base doubles: with fixed jitter the observed sleeps must grow
    assert sleeps[1] > sleeps[0] and sleeps[2] > sleeps[1]
