/* fork() under the simulator: the child gets its own driver channel
 * (PSYS_FORK pre-creates it; the shim's fork interposition adopts it in
 * the child), opens a UDP socket on the SAME simulated host, and talks to
 * the parent over the simulated loopback path. The parent waits for the
 * child via the driver-emulated waitpid. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static long long now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(void) {
  int parent_sock = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(7100);
  if (bind(parent_sock, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }

  pid_t pid = fork();
  if (pid < 0) {
    perror("fork");
    return 1;
  }
  if (pid == 0) {
    // child: send two datagrams to the parent, then exit 7
    int s = socket(AF_INET, SOCK_DGRAM, 0);
    struct sockaddr_in dst;
    memset(&dst, 0, sizeof(dst));
    dst.sin_family = AF_INET;
    dst.sin_addr.s_addr = htonl(0x7F000001);
    dst.sin_port = htons(7100);
    for (int i = 0; i < 2; i++) {
      char msg[32];
      int n = snprintf(msg, sizeof(msg), "child msg %d", i);
      sendto(s, msg, n, 0, (struct sockaddr*)&dst, sizeof(dst));
      struct timespec d = {0, 5000000};
      nanosleep(&d, 0);
    }
    printf("child done at %lld\n", now_ns());
    return 7;
  }
  // parent: receive both, then reap the child
  for (int i = 0; i < 2; i++) {
    char buf[64];
    ssize_t n = recvfrom(parent_sock, buf, sizeof(buf) - 1, 0, 0, 0);
    if (n < 0) {
      perror("recvfrom");
      return 1;
    }
    buf[n] = 0;
    printf("parent got '%s' at %lld\n", buf, now_ns());
  }
  int st = 0;
  pid_t r = waitpid(pid, &st, 0);
  printf("reaped pid %s status %d at %lld\n", r == pid ? "ok" : "BAD",
         WEXITSTATUS(st), now_ns());
  return 0;
}
