/* CPU-count pseudo-files must reflect the SIMULATED host's CPU count. */
#include <stdio.h>
#include <string.h>

static int count_processors(void) {
  FILE* f = fopen("/proc/cpuinfo", "r");
  if (!f) return -1;
  char line[256];
  int n = 0;
  while (fgets(line, sizeof(line), f))
    if (strncmp(line, "processor", 9) == 0) n++;
  fclose(f);
  return n;
}

int main(void) {
  printf("cpuinfo %d\n", count_processors());
  FILE* f = fopen("/sys/devices/system/cpu/online", "r");
  char buf[64] = "?";
  if (f) {
    if (!fgets(buf, sizeof(buf), f)) buf[0] = '?';
    fclose(f);
    buf[strcspn(buf, "\n")] = 0;
  }
  printf("online %s\n", buf);
  /* a non-virtualized file still opens natively through the trap */
  FILE* g = fopen("/proc/version", "r");
  printf("other %d\n", g != NULL);
  if (g) fclose(g);
  return 0;
}
