/* Raw rdtsc/rdtscp under the simulator (host/tsc.c analog): both must
 * read the VIRTUAL clock — deterministic, advancing only with sim time.
 * Prints tsc values around a nanosleep; the test asserts exact values. */
#include <stdint.h>
#include <stdio.h>
#include <time.h>

static inline uint64_t rdtsc(void) {
  uint32_t lo, hi;
  __asm__ volatile("rdtsc" : "=a"(lo), "=d"(hi));
  return ((uint64_t)hi << 32) | lo;
}

static inline uint64_t rdtscp(void) {
  uint32_t lo, hi, aux;
  __asm__ volatile("rdtscp" : "=a"(lo), "=d"(hi), "=c"(aux));
  return ((uint64_t)hi << 32) | lo;
}

int main(void) {
  /* one syscall first so the channel's sim-time stamp is fresh */
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  uint64_t a = rdtsc();
  /* no syscall between reads: the virtual TSC advances by exactly one
   * cycle per read past the channel stamp (deterministic, and it keeps
   * pure-rdtsc delay loops terminating instead of spinning on a frozen
   * clock) */
  uint64_t b = rdtsc();
  uint64_t c = rdtscp();
  printf("tsc-a %llu\n", (unsigned long long)a);
  printf("tsc-mono %d\n", b == a + 1 && c == b + 1);
  struct timespec d = {0, 250 * 1000 * 1000}; /* 250 ms on the sim clock */
  nanosleep(&d, NULL);
  clock_gettime(CLOCK_MONOTONIC, &ts);
  uint64_t e = rdtsc();
  printf("tsc-delta %llu\n", (unsigned long long)(e - a));
  return 0;
}
