/* Exit server for the multi-hop relay e2e: accepts connections, reads a
 * "GET <nbytes>\n" request, streams nbytes of deterministic data back,
 * half-closes. poll()-multiplexed like relay.c.
 *
 * Usage: circuit_server <port> [lifetime_s]
 */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define MAX_SESS 512
#define BUF 4096

typedef struct {
  int fd;
  char req[64];
  int req_n;
  long remaining; /* -1 until the request parses */
} Sess;

static Sess sess[MAX_SESS];
static int nsess = 0;

static void drop(int i) {
  close(sess[i].fd);
  sess[i] = sess[--nsess];
}

int main(int argc, char** argv) {
  if (argc < 2) return 2;
  int port = atoi(argv[1]);
  int life = argc > 2 ? atoi(argv[2]) : 0;
  time_t t0 = time(NULL);
  int ls = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(ls, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a = {0};
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  if (bind(ls, (struct sockaddr*)&a, sizeof a) != 0) {
    perror("bind");
    return 1;
  }
  listen(ls, 256);
  printf("server up %d\n", port);
  fflush(stdout);
  int served = 0;
  char chunk[BUF];
  for (size_t i = 0; i < sizeof chunk; i++) chunk[i] = (char)('a' + i % 26);

  for (;;) {
    if (life && time(NULL) - t0 >= life) break;
    struct pollfd pf[1 + MAX_SESS];
    int n = 0;
    pf[n].fd = ls;
    pf[n].events = nsess < MAX_SESS ? POLLIN : 0;
    n++;
    for (int i = 0; i < nsess; i++) {
      pf[n].fd = sess[i].fd;
      pf[n].events = sess[i].remaining < 0 ? POLLIN : POLLOUT;
      n++;
    }
    if (poll(pf, n, 1000) < 0) break;
    if (pf[0].revents & POLLIN) {
      int c = accept(ls, NULL, NULL);
      if (c >= 0 && nsess < MAX_SESS) {
        Sess* s = &sess[nsess++];
        memset(s, 0, sizeof *s);
        s->fd = c;
        s->remaining = -1;
      } else if (c >= 0) {
        close(c);
      }
    }
    for (int k = 1; k < n; k++) {
      int i = k - 1;
      if (i >= nsess) continue;
      Sess* s = &sess[i];
      if (pf[k].fd != s->fd || !pf[k].revents) continue;
      if (s->remaining < 0) {
        ssize_t r = read(s->fd, s->req + s->req_n,
                         sizeof(s->req) - 1 - s->req_n);
        if (r <= 0) {
          drop(i);
          continue;
        }
        s->req_n += (int)r;
        s->req[s->req_n] = 0;
        char* nl = strchr(s->req, '\n');
        if (!nl) continue;
        long want = 0;
        if (sscanf(s->req, "GET %ld", &want) != 1 || want < 0) {
          drop(i);
          continue;
        }
        s->remaining = want;
      } else if (s->remaining > 0) {
        size_t m = s->remaining < (long)sizeof chunk ? (size_t)s->remaining
                                                     : sizeof chunk;
        ssize_t w = write(s->fd, chunk, m);
        if (w <= 0) {
          drop(i);
          continue;
        }
        s->remaining -= w;
      }
      if (s->remaining == 0) {
        served++;
        printf("served %d\n", served);
        fflush(stdout);
        drop(i);
      }
    }
  }
  printf("server done %d\n", served);
  return 0;
}
