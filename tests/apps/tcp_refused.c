/* Connects to <server>:<port>; expects ECONNREFUSED; prints the result.
 * Usage: tcp_refused <server> <port> */
#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char** argv) {
  const char* server = argc > 1 ? argv[1] : "server";
  const char* port = argc > 2 ? argv[2] : "9999";
  struct addrinfo hints, *res = NULL;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(server, port, &hints, &res) != 0 || !res) {
    fprintf(stderr, "resolve failed\n");
    return 1;
  }
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); return 1; }
  int r = connect(fd, res->ai_addr, res->ai_addrlen);
  if (r == 0) {
    printf("connected\n");
  } else if (errno == ECONNREFUSED) {
    printf("refused\n");
  } else {
    printf("error %d\n", errno);
  }
  close(fd);
  freeaddrinfo(res);
  return 0;
}
