/* Minimal UDP echo client: resolves SERVER by name (exercises the DNS
 * pseudo-syscall), sends N pings, prints each round-trip time measured with
 * the VIRTUAL clock. Usage: udp_echo_client <server> <port> <count> */
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static long long now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(int argc, char** argv) {
  const char* server = argc > 1 ? argv[1] : "server";
  const char* port = argc > 2 ? argv[2] : "9000";
  int count = argc > 3 ? atoi(argv[3]) : 1;

  struct addrinfo hints, *res = NULL;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_DGRAM;
  if (getaddrinfo(server, port, &hints, &res) != 0 || !res) {
    fprintf(stderr, "resolve failed\n");
    return 1;
  }
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) { perror("socket"); return 1; }

  char msg[64], buf[2048];
  for (int i = 0; i < count; i++) {
    int n = snprintf(msg, sizeof(msg), "ping %d", i);
    long long t0 = now_ns();
    if (sendto(fd, msg, n, 0, res->ai_addr, res->ai_addrlen) != n) {
      perror("sendto");
      return 1;
    }
    ssize_t r = recvfrom(fd, buf, sizeof(buf), 0, NULL, NULL);
    long long t1 = now_ns();
    if (r != n || memcmp(buf, msg, n) != 0) {
      fprintf(stderr, "bad echo\n");
      return 1;
    }
    printf("rtt %lld ns\n", t1 - t0);
  }
  freeaddrinfo(res);
  close(fd);
  printf("client done\n");
  return 0;
}
