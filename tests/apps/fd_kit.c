/* Exercises the non-socket descriptor kit under the simulator: pipes,
 * eventfd, timerfd, dup, getrandom, readv/writev. Prints a deterministic
 * transcript; exits nonzero on any misbehavior. */
#include <poll.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/random.h>
#include <sys/timerfd.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

static long long now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

int main(void) {
  /* ---- pipe + poll + dup ---- */
  int p[2];
  if (pipe(p)) { perror("pipe"); return 1; }
  struct pollfd pf = {p[0], POLLIN, 0};
  if (poll(&pf, 1, 0) != 0) { fprintf(stderr, "pipe early ready\n"); return 1; }
  struct iovec iov[2] = {{(void*)"hel", 3}, {(void*)"lo", 2}};
  if (writev(p[1], iov, 2) != 5) { perror("writev"); return 1; }
  if (poll(&pf, 1, 1000) != 1 || !(pf.revents & POLLIN)) {
    fprintf(stderr, "pipe not readable\n");
    return 1;
  }
  int pdup = dup(p[0]);
  char b0[3], b1[4];
  struct iovec riov[2] = {{b0, 3}, {b1, 2}};
  if (readv(pdup, riov, 2) != 5 || memcmp(b0, "hel", 3) || memcmp(b1, "lo", 2)) {
    fprintf(stderr, "readv mismatch\n");
    return 1;
  }
  close(pdup);
  if (write(p[1], "x", 1) != 1) { perror("pipe write after dup close"); return 1; }
  char c;
  if (read(p[0], &c, 1) != 1 || c != 'x') { fprintf(stderr, "bad pipe byte\n"); return 1; }
  close(p[1]);
  if (read(p[0], &c, 1) != 0) { fprintf(stderr, "no EOF after close\n"); return 1; }
  close(p[0]);
  printf("pipe ok\n");

  /* ---- eventfd ---- */
  int ev = eventfd(2, 0);
  uint64_t v = 0;
  if (read(ev, &v, 8) != 8 || v != 2) { fprintf(stderr, "eventfd v=%llu\n", (unsigned long long)v); return 1; }
  v = 5;
  if (write(ev, &v, 8) != 8) { perror("eventfd write"); return 1; }
  v = 3;
  if (write(ev, &v, 8) != 8) { perror("eventfd write2"); return 1; }
  if (read(ev, &v, 8) != 8 || v != 8) { fprintf(stderr, "eventfd sum=%llu\n", (unsigned long long)v); return 1; }
  close(ev);
  printf("eventfd ok\n");

  /* ---- timerfd: 3 ticks of exactly 50 ms on the virtual clock ---- */
  int tf = timerfd_create(CLOCK_MONOTONIC, 0);
  struct itimerspec its = {{0, 50000000}, {0, 50000000}};
  if (timerfd_settime(tf, 0, &its, NULL)) { perror("settime"); return 1; }
  int ep = epoll_create1(0);
  struct epoll_event e = {EPOLLIN, {.fd = tf}};
  epoll_ctl(ep, EPOLL_CTL_ADD, tf, &e);
  long long t_prev = now_ns();
  for (int i = 0; i < 3; i++) {
    struct epoll_event out;
    if (epoll_wait(ep, &out, 1, 2000) != 1) { fprintf(stderr, "timer wait\n"); return 1; }
    uint64_t ticks;
    if (read(tf, &ticks, 8) != 8 || ticks != 1) { fprintf(stderr, "ticks=%llu\n", (unsigned long long)ticks); return 1; }
    long long t = now_ns();
    printf("tick %d dt %lld ns\n", i, t - t_prev);
    t_prev = t;
  }
  close(tf);
  close(ep);

  /* ---- getrandom: deterministic under the simulator ---- */
  unsigned char rnd[8];
  if (getrandom(rnd, 8, 0) != 8) { perror("getrandom"); return 1; }
  printf("rand ");
  for (int i = 0; i < 8; i++) printf("%02x", rnd[i]);
  printf("\nfd kit done\n");
  return 0;
}
