/* Wider-syscall-surface probe (VERDICT r4 #5 + r5 tranche): stat family
 * on managed fds, getifaddrs, deterministic localtime, mmap policy,
 * /proc/self/fd (reopen + directory listing), signalfd, ppoll sigmask,
 * deterministic rlimits/rusage.
 * Prints one "ok <probe>" line per passing probe; exits nonzero on the
 * first failure so the driver test can grep like verify.sh does. */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <ifaddrs.h>
#include <net/if.h>
#include <poll.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/eventfd.h>
#include <sys/mman.h>
#include <sys/resource.h>
#include <sys/signalfd.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <linux/stat.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

static volatile int g_usr2_hits = 0;
static void on_usr2(int sig) { (void)sig; g_usr2_hits++; }

static int fail(const char* what) {
  fprintf(stderr, "FAIL %s: %s\n", what, strerror(errno));
  return 1;
}

int main(void) {
  /* ---- fstat on managed fds ---- */
  int s = socket(AF_INET, SOCK_DGRAM, 0);
  if (s < 0) return fail("socket");
  struct stat st;
  if (fstat(s, &st) != 0) return fail("fstat(sock)");
  if (!S_ISSOCK(st.st_mode)) return fail("fstat(sock) mode");
  printf("ok fstat-sock\n");

  int pfd[2];
  if (pipe(pfd) != 0) return fail("pipe");
  if (fstat(pfd[0], &st) != 0) return fail("fstat(pipe)");
  if (!S_ISFIFO(st.st_mode)) return fail("fstat(pipe) mode");
  printf("ok fstat-pipe\n");

  int efd = eventfd(0, 0);
  if (efd < 0 || fstat(efd, &st) != 0) return fail("fstat(eventfd)");
  printf("ok fstat-eventfd\n");

  /* path-based stat: glibc compiles this to newfstatat(AT_FDCWD, ...) —
   * the negative dirfd traps the fd-discriminating filter and must
   * complete through the gate (one SIGSYS round trip), not recurse */
  if (stat("/", &st) != 0 || !S_ISDIR(st.st_mode)) return fail("stat(/)");
  printf("ok stat-path\n");

  /* statx with AT_EMPTY_PATH on a managed fd (the Rust/modern-glibc
   * stat entry point) */
  struct statx stx;
  if (statx(s, "", AT_EMPTY_PATH, STATX_TYPE | STATX_MODE, &stx) != 0)
    return fail("statx(sock)");
  if (!S_ISSOCK(stx.stx_mode)) return fail("statx(sock) mode");
  printf("ok statx\n");

  /* raw SYS_statx (the seccomp-trap path Rust std uses — no PLT): the
   * argument marshaling through route_raw_syscall must match */
  memset(&stx, 0, sizeof stx);
  if (syscall(SYS_statx, s, "", AT_EMPTY_PATH,
              STATX_TYPE | STATX_MODE, &stx) != 0)
    return fail("raw statx(sock)");
  if (!S_ISSOCK(stx.stx_mode)) return fail("raw statx mode");
  printf("ok statx-raw\n");

  /* ---- getifaddrs: lo + eth0 with the simulated address ---- */
  struct ifaddrs* ifa = NULL;
  if (getifaddrs(&ifa) != 0) return fail("getifaddrs");
  int saw_lo = 0;
  char eth_ip[64] = "";
  for (struct ifaddrs* p = ifa; p; p = p->ifa_next) {
    if (!p->ifa_addr || p->ifa_addr->sa_family != AF_INET) continue;
    struct sockaddr_in* sin = (struct sockaddr_in*)p->ifa_addr;
    if (p->ifa_flags & IFF_LOOPBACK) {
      saw_lo = 1;
    } else {
      inet_ntop(AF_INET, &sin->sin_addr, eth_ip, sizeof eth_ip);
    }
  }
  freeifaddrs(ifa);
  if (!saw_lo || !eth_ip[0]) return fail("getifaddrs entries");
  printf("ok getifaddrs %s\n", eth_ip);

  /* ---- localtime: simulated clock, UTC, deterministic ---- */
  time_t t = time(NULL);
  struct tm tmv;
  if (!localtime_r(&t, &tmv)) return fail("localtime_r");
  printf("ok localtime %ld %04d-%02d-%02d %02d:%02d:%02d\n", (long)t,
         tmv.tm_year + 1900, tmv.tm_mon + 1, tmv.tm_mday, tmv.tm_hour,
         tmv.tm_min, tmv.tm_sec);

  /* ---- mmap policy ---- */
  void* anon = mmap(NULL, 4096, PROT_READ | PROT_WRITE,
                    MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (anon == MAP_FAILED) return fail("mmap(anon)");
  ((char*)anon)[0] = 1;
  munmap(anon, 4096);
  printf("ok mmap-anon\n");

  char tmpl[] = "/tmp/shadow_mmap_XXXXXX";
  int tf = mkstemp(tmpl);
  if (tf < 0) return fail("mkstemp");
  if (ftruncate(tf, 4096) != 0) return fail("ftruncate");
  void* shared = mmap(NULL, 4096, PROT_READ | PROT_WRITE, MAP_SHARED, tf, 0);
  if (shared != MAP_FAILED || errno != EACCES) {
    fprintf(stderr, "FAIL mmap policy: writable MAP_SHARED allowed\n");
    return 1;
  }
  void* ro = mmap(NULL, 4096, PROT_READ, MAP_SHARED, tf, 0);
  if (ro == MAP_FAILED) return fail("mmap(ro-shared)");
  munmap(ro, 4096);
  close(tf);
  unlink(tmpl);
  printf("ok mmap-policy\n");

  if (mmap(NULL, 4096, PROT_READ, MAP_SHARED, s, 0) != MAP_FAILED ||
      errno != ENODEV) {
    fprintf(stderr, "FAIL mmap(managed fd) allowed\n");
    return 1;
  }
  printf("ok mmap-managed-denied\n");

  /* ---- /proc/self/fd on a managed fd: reopen == dup ---- */
  char path[64];
  snprintf(path, sizeof path, "/proc/self/fd/%d", pfd[1]);
  int wdup = open(path, O_WRONLY);
  if (wdup < 0) return fail("open(/proc/self/fd)");
  if (write(wdup, "x", 1) != 1) return fail("write(dup)");
  char c = 0;
  if (read(pfd[0], &c, 1) != 1 || c != 'x') return fail("read(pipe)");
  close(wdup);
  printf("ok proc-self-fd\n");

  /* ---- /proc/self/fd directory LISTING includes managed fds ---- */
  DIR* dir = opendir("/proc/self/fd");
  if (!dir) return fail("opendir(/proc/self/fd)");
  int dfd = dirfd(dir); /* the canonical sweep skips this entry */
  if (dfd < 0) return fail("dirfd");
  int saw_sock = 0, saw_pipe = 0;
  struct dirent* de;
  while ((de = readdir(dir))) {
    long fd = strtol(de->d_name, NULL, 10);
    if (fd == s) saw_sock = 1;
    if (fd == pfd[0]) saw_pipe = 1;
  }
  rewinddir(dir); /* replay must see the managed entries again */
  int saw_sock2 = 0;
  while ((de = readdir(dir)))
    if (strtol(de->d_name, NULL, 10) == s) saw_sock2 = 1;
  if (!saw_sock2) return fail("rewinddir replay");
  closedir(dir);
  if (!saw_sock || !saw_pipe) {
    fprintf(stderr, "FAIL fd listing: sock=%d pipe=%d\n", saw_sock,
            saw_pipe);
    return 1;
  }
  printf("ok proc-fd-listing\n");

  /* ---- signalfd on the virtual signal plane ---- */
  sigset_t sfd_set;
  sigemptyset(&sfd_set);
  sigaddset(&sfd_set, SIGUSR1);
  if (sigprocmask(SIG_BLOCK, &sfd_set, NULL) != 0)
    return fail("sigprocmask(block USR1)");
  int sfd = signalfd(-1, &sfd_set, SFD_NONBLOCK);
  if (sfd < 0) return fail("signalfd");
  struct signalfd_siginfo ssi;
  if (read(sfd, &ssi, sizeof ssi) != -1 || errno != EAGAIN)
    return fail("signalfd empty read");
  raise(SIGUSR1); /* blocked: stays pending, consumable via the fd */
  struct pollfd spf = {.fd = sfd, .events = POLLIN};
  if (poll(&spf, 1, 1000) != 1 || !(spf.revents & POLLIN))
    return fail("poll(signalfd)");
  if (read(sfd, &ssi, sizeof ssi) != sizeof ssi)
    return fail("signalfd read");
  if (ssi.ssi_signo != SIGUSR1) {
    fprintf(stderr, "FAIL signalfd signo %u\n", ssi.ssi_signo);
    return 1;
  }
  close(sfd);
  printf("ok signalfd\n");

  /* the canonical pattern: block SIGCHLD (a default-IGNORE signal — it
   * must stay PENDING while blocked, not be discarded), fork, consume
   * the child's exit through the fd */
  sigset_t chld;
  sigemptyset(&chld);
  sigaddset(&chld, SIGCHLD);
  if (sigprocmask(SIG_BLOCK, &chld, NULL) != 0)
    return fail("sigprocmask(block CHLD)");
  int cfd = signalfd(-1, &chld, 0);
  if (cfd < 0) return fail("signalfd(chld)");
  pid_t kid = fork();
  if (kid < 0) return fail("fork");
  if (kid == 0) _exit(0);
  struct pollfd cpf = {.fd = cfd, .events = POLLIN};
  if (poll(&cpf, 1, 5000) != 1 || !(cpf.revents & POLLIN))
    return fail("poll(signalfd chld)");
  if (read(cfd, &ssi, sizeof ssi) != sizeof ssi || ssi.ssi_signo != SIGCHLD)
    return fail("signalfd chld read");
  close(cfd);
  if (waitpid(kid, NULL, 0) != kid) return fail("waitpid");
  printf("ok signalfd-chld\n");

  /* ---- ppoll: pending signal unblocked by the sigmask swap -> EINTR,
   * handler invoked (the atomic mask-swap contract) ---- */
  signal(SIGUSR2, on_usr2);
  sigset_t blk;
  sigemptyset(&blk);
  sigaddset(&blk, SIGUSR2);
  if (sigprocmask(SIG_BLOCK, &blk, NULL) != 0)
    return fail("sigprocmask(block USR2)");
  raise(SIGUSR2); /* pending while blocked */
  if (g_usr2_hits != 0) return fail("USR2 delivered while blocked");
  sigset_t none;
  sigemptyset(&none);
  struct timespec pts = {.tv_sec = 2, .tv_nsec = 0};
  struct pollfd ppf = {.fd = pfd[0], .events = POLLIN};
  int pr = ppoll(&ppf, 1, &pts, &none); /* unblocks USR2 for the wait */
  if (pr != -1 || errno != EINTR) {
    fprintf(stderr, "FAIL ppoll: ret=%d errno=%d hits=%d\n", pr, errno,
            g_usr2_hits);
    return 1;
  }
  if (g_usr2_hits != 1) return fail("ppoll handler count");
  printf("ok ppoll-sigmask\n");

  /* ---- deterministic resource limits + usage ---- */
  struct rlimit rl;
  if (getrlimit(RLIMIT_NOFILE, &rl) != 0) return fail("getrlimit");
  printf("ok rlimit-nofile %llu %llu\n", (unsigned long long)rl.rlim_cur,
         (unsigned long long)rl.rlim_max);
  struct rlimit nl = {.rlim_cur = 512, .rlim_max = rl.rlim_max};
  if (setrlimit(RLIMIT_NOFILE, &nl) != 0) return fail("setrlimit");
  struct rlimit back;
  if (prlimit(0, RLIMIT_NOFILE, NULL, &back) != 0) return fail("prlimit");
  if (back.rlim_cur != 512) return fail("prlimit readback");
  printf("ok rlimit-roundtrip\n");
  struct rusage ru;
  if (getrusage(RUSAGE_SELF, &ru) != 0) return fail("getrusage");
  printf("ok rusage %ld.%06ld %ld\n", (long)ru.ru_utime.tv_sec,
         (long)ru.ru_utime.tv_usec, ru.ru_maxrss);

  printf("wide done\n");
  return 0;
}
