/* Circuit client for the multi-hop relay e2e (tor-minimal analog,
 * verify.sh:7-22 grep protocol): builds an onion-style circuit through
 * relays, requests nbytes from the exit server, and prints one
 * "stream-success" per completed stream.
 *
 * Usage: circuit_client <entry_host> <entry_port> <circuit> <streams> <nbytes>
 *   circuit = "hop2:port/hop3:port/exit:port/" (hops AFTER the entry)
 */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netdb.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

static int conn_to(const char* host, const char* port) {
  struct addrinfo hints = {0}, *ai = NULL;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host, port, &hints, &ai) != 0 || !ai) return -1;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0 || connect(fd, ai->ai_addr, ai->ai_addrlen) != 0) {
    if (fd >= 0) close(fd);
    freeaddrinfo(ai);
    return -1;
  }
  freeaddrinfo(ai);
  return fd;
}

int main(int argc, char** argv) {
  if (argc < 6) return 2;
  const char* entry = argv[1];
  const char* eport = argv[2];
  const char* circuit = argv[3];
  int streams = atoi(argv[4]);
  long nbytes = atol(argv[5]);
  int ok = 0;
  for (int s = 0; s < streams; s++) {
    int fd = conn_to(entry, eport);
    if (fd < 0) {
      fprintf(stderr, "stream %d: connect failed\n", s);
      continue;
    }
    char req[640];
    int m = snprintf(req, sizeof req, "%s\nGET %ld\n", circuit, nbytes);
    ssize_t off = 0;
    while (off < m) {
      ssize_t w = write(fd, req + off, (size_t)(m - off));
      if (w <= 0) break;
      off += w;
    }
    long got = 0;
    char buf[4096];
    for (;;) {
      ssize_t r = read(fd, buf, sizeof buf);
      if (r <= 0) break;
      got += r;
    }
    close(fd);
    if (got == nbytes) {
      printf("stream-success %d %ld\n", s, got);
      ok++;
    } else {
      printf("stream-fail %d %ld/%ld\n", s, got, nbytes);
    }
  }
  printf("client done %d/%d\n", ok, streams);
  return ok == streams ? 0 : 1;
}
