/* Edge-triggered epoll semantics (reference: epoll.c:162-227 edge/level):
 * arm EPOLLIN|EPOLLET on a UDP socket, let TWO datagrams arrive while NOT
 * draining between waits. Level-triggered would report readiness again on
 * the second wait without new data; edge-triggered must NOT — and must
 * report again after a THIRD datagram (a fresh edge).
 * Usage: epollet <port>   (peer sends 2 datagrams, pause, then 1 more) */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <fcntl.h>
#include <time.h>
#include <unistd.h>

static int send_mode(const char* ip, int port) {
  int s = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof(dst));
  dst.sin_family = AF_INET;
  inet_pton(AF_INET, ip, &dst.sin_addr);
  dst.sin_port = htons(port);
  sendto(s, "a", 1, 0, (struct sockaddr*)&dst, sizeof(dst));
  struct timespec d = {0, 200000000};
  nanosleep(&d, 0);  // let wait1 report the first edge
  sendto(s, "b", 1, 0, (struct sockaddr*)&dst, sizeof(dst));
  struct timespec d2 = {2, 0};
  nanosleep(&d2, 0);
  sendto(s, "c", 1, 0, (struct sockaddr*)&dst, sizeof(dst));
  return 0;
}

int main(int argc, char** argv) {
  setvbuf(stdout, 0, _IOLBF, 0);
  if (argc >= 4 && strcmp(argv[1], "--send") == 0)
    return send_mode(argv[2], atoi(argv[3]));
  int port = argc > 1 ? atoi(argv[1]) : 7300;
  int s = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(s, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  int ep = epoll_create1(0);
  struct epoll_event ev;
  ev.events = EPOLLIN | EPOLLET;
  ev.data.fd = s;
  epoll_ctl(ep, EPOLL_CTL_ADD, s, &ev);

  // wait #1: first datagram arrives -> edge, reported
  int n1 = epoll_wait(ep, &ev, 4, 5000);
  printf("wait1 %d\n", n1);
  // do NOT drain; wait #2 with a short timeout: a second datagram arrived
  // by now, which IS a new edge -> reported once
  int n2 = epoll_wait(ep, &ev, 4, 1000);
  printf("wait2 %d\n", n2);
  // wait #3 without new data since wait2's report: must time out (0)
  int n3 = epoll_wait(ep, &ev, 4, 300);
  printf("wait3 %d\n", n3);
  // drain both datagrams (nonblocking via fcntl)
  fcntl(s, F_SETFL, O_NONBLOCK);
  char buf[512];
  while (recv(s, buf, sizeof(buf), 0) > 0) {
  }
  fcntl(s, F_SETFL, 0);
  // wait #4: the peer's third datagram (sent after a 2s pause) is a fresh
  // edge -> reported
  int n4 = epoll_wait(ep, &ev, 4, 5000);
  printf("wait4 %d\n", n4);
  return 0;
}
