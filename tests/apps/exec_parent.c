/* fork + execve under the simulator: the parent binds a UDP port, forks,
 * and the child execs exec_child (path passed as argv[1]), which must run
 * MANAGED (virtual clock, simulated network) despite the inherited seccomp
 * filter — the fd-argument BPF tests let the fresh ld.so boot, and the
 * re-LD_PRELOADed shim re-attaches on the inherited channel. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: exec_parent <exec_child path>\n");
    return 2;
  }
  int s = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(7200);
  if (bind(s, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  pid_t pid = fork();
  if (pid == 0) {
    char* cargv[] = {argv[1], (char*)"7200", 0};
    execv(argv[1], cargv);
    perror("execv");
    _exit(127);
  }
  char buf[64];
  ssize_t n = recvfrom(s, buf, sizeof(buf) - 1, 0, 0, 0);
  if (n < 0) {
    perror("recvfrom");
    return 1;
  }
  buf[n] = 0;
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  printf("parent got '%s' at %lld\n", buf,
         (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec);
  waitpid(pid, 0, 0);
  printf("parent done\n");
  return 0;
}
