/* Exercises RAW syscall instructions (via libc syscall(2), which issues the
 * instruction from libc — NOT the shim's interposed symbols). Without the
 * seccomp/SIGSYS backstop these would hit the real kernel and see real
 * time / real sockets; with it they are trapped and routed to the
 * simulator. Prints the virtual clock and echoes a datagram.
 * Usage: raw_syscalls <server-ip> <port> <count>   (client)
 *        raw_syscalls --server <port> <count>      (server) */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/syscall.h>
#include <time.h>
#include <unistd.h>

static long raw(long n, long a, long b, long c, long d, long e, long f) {
  return syscall(n, a, b, c, d, e, f);
}

int main(int argc, char** argv) {
  int server = argc > 1 && strcmp(argv[1], "--server") == 0;
  int port = argc > 2 ? atoi(argv[2]) : 9000;
  int count = argc > 3 ? atoi(argv[3]) : 2;

  struct timespec ts;
  raw(SYS_clock_gettime, CLOCK_REALTIME, (long)&ts, 0, 0, 0, 0);
  printf("t0 %lld\n", (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec);

  int fd = (int)raw(SYS_socket, AF_INET, SOCK_DGRAM, 0, 0, 0, 0);
  if (fd < 0) { perror("raw socket"); return 1; }

  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);

  char buf[512];
  if (server) {
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    if (raw(SYS_bind, fd, (long)&addr, sizeof(addr), 0, 0, 0) != 0) {
      perror("raw bind");
      return 1;
    }
    for (int i = 0; i < count; i++) {
      struct sockaddr_in src;
      socklen_t slen = sizeof(src);
      long n = raw(SYS_recvfrom, fd, (long)buf, sizeof(buf), 0, (long)&src,
                   (long)&slen);
      if (n < 0) { perror("raw recvfrom"); return 1; }
      raw(SYS_sendto, fd, (long)buf, n, 0, (long)&src, slen);
    }
    printf("served %d\n", count);
  } else {
    inet_aton(argv[1], &addr.sin_addr);
    /* raw nanosleep so send times are deterministic on the virtual clock */
    struct timespec d = {0, 250000000};
    for (int i = 0; i < count; i++) {
      raw(SYS_nanosleep, (long)&d, 0, 0, 0, 0, 0);
      snprintf(buf, sizeof(buf), "ping %d", i);
      if (raw(SYS_sendto, fd, (long)buf, strlen(buf), 0, (long)&addr,
              sizeof(addr)) < 0) {
        perror("raw sendto");
        return 1;
      }
      long n = raw(SYS_recvfrom, fd, (long)buf, sizeof(buf), 0, 0, 0);
      if (n < 0) { perror("raw recvfrom"); return 1; }
      raw(SYS_clock_gettime, CLOCK_REALTIME, (long)&ts, 0, 0, 0, 0);
      printf("echo %d at %lld\n", i,
             (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec);
    }
  }
  raw(SYS_close, fd, 0, 0, 0, 0, 0);
  return 0;
}
