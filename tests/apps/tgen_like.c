/* tgen-like multi-stream transfer workload (reference analog: the tor
 * integration test's tgen client/server pairs, src/test/tor/minimal:
 * verify.sh greps for "stream-success" counts). Runs as a real managed
 * process over the simulated network (device TCP when use_device_tcp).
 *
 * server: tgen_like --server <port> <nstreams>
 *   accepts nstreams connections; per connection reads "SEND <n>\n" and
 *   writes n bytes back, then closes; prints "stream-served <n>".
 * client: tgen_like <server-base> <server-count> <port> <streams> <bytes>
 *   picks a server deterministically from its own (simulated) hostname,
 *   then runs <streams> sequential downloads of <bytes> each; prints
 *   "stream-success <i> <bytes> at <virtual ns>" per completed stream and
 *   "transfers-complete <streams>" at the end. */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <sys/epoll.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

static long long now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static int read_n(int fd, char* buf, long long n) {
  long long got = 0;
  while (got < n) {
    ssize_t r = recv(fd, buf, (size_t)((n - got) > 4096 ? 4096 : n - got), 0);
    if (r <= 0) return -1;
    got += r;
  }
  return 0;
}

/* Event-driven concurrent server (tgen/tor are libevent-style: many
 * simultaneous streams multiplex over one epoll loop). */
#define MAXCONN 256

struct conn {
  int fd;
  int phase;  /* 0 = reading request, 1 = sending */
  int roff;
  char req[64];
  long long want, sent;
};

static int run_server(int port, int nstreams) {
  int ls = socket(AF_INET, SOCK_STREAM, 0);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(ls, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(ls, 128) != 0) {
    perror("listen");
    return 1;
  }
  fcntl(ls, F_SETFL, O_NONBLOCK);
  int ep = epoll_create1(0);
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.u64 = 0;  /* listener */
  epoll_ctl(ep, EPOLL_CTL_ADD, ls, &ev);

  static struct conn conns[MAXCONN];
  char buf[4096];
  memset(buf, 'd', sizeof(buf));
  int served = 0;
  struct epoll_event evs[32];
  while (nstreams <= 0 || served < nstreams) {
    int n = epoll_wait(ep, evs, 32, 30000);
    if (n <= 0) break;
    for (int e = 0; e < n; e++) {
      if (evs[e].data.u64 == 0) {
        for (;;) {
          int c = accept(ls, 0, 0);
          if (c < 0) break;
          fcntl(c, F_SETFL, O_NONBLOCK);
          int slot = -1;
          for (int j = 0; j < MAXCONN; j++)
            if (conns[j].fd == 0) {
              slot = j;
              break;
            }
          if (slot < 0) {
            close(c);
            continue;
          }
          memset(&conns[slot], 0, sizeof(struct conn));
          conns[slot].fd = c;
          struct epoll_event cev;
          cev.events = EPOLLIN;
          cev.data.u64 = (unsigned)slot + 1;
          epoll_ctl(ep, EPOLL_CTL_ADD, c, &cev);
        }
        continue;
      }
      struct conn* cn = &conns[evs[e].data.u64 - 1];
      if (cn->fd == 0) continue;
      if (cn->phase == 0) {
        for (;;) {
          ssize_t r = recv(cn->fd, cn->req + cn->roff, 1, 0);
          if (r <= 0) break;
          if (cn->req[cn->roff] == '\n' ||
              cn->roff >= (int)sizeof(cn->req) - 2) {
            cn->req[cn->roff] = 0;
            sscanf(cn->req, "SEND %lld", &cn->want);
            cn->phase = 1;
            struct epoll_event cev;
            cev.events = EPOLLOUT;
            cev.data.u64 = evs[e].data.u64;
            epoll_ctl(ep, EPOLL_CTL_MOD, cn->fd, &cev);
            break;
          }
          cn->roff++;
        }
      }
      if (cn->phase == 1 && (evs[e].events & EPOLLOUT)) {
        while (cn->sent < cn->want) {
          size_t chunk = (size_t)((cn->want - cn->sent) >
                                          (long long)sizeof(buf)
                                      ? (long long)sizeof(buf)
                                      : cn->want - cn->sent);
          ssize_t r = send(cn->fd, buf, chunk, 0);
          if (r <= 0) break;  /* EAGAIN: wait for the next EPOLLOUT */
          cn->sent += r;
        }
        if (cn->sent >= cn->want) {
          epoll_ctl(ep, EPOLL_CTL_DEL, cn->fd, 0);
          close(cn->fd);
          printf("stream-served %lld\n", cn->sent);
          cn->fd = 0;
          served++;
        }
      }
    }
  }
  printf("server-done %d\n", served);
  return 0;
}

int main(int argc, char** argv) {
  // line-buffer stdout even when piped: a sim-stop ends us via _exit,
  // which would discard block-buffered progress lines
  setvbuf(stdout, 0, _IOLBF, 0);
  if (argc >= 2 && strcmp(argv[1], "--server") == 0) {
    return run_server(argc > 2 ? atoi(argv[2]) : 9100,
                      argc > 3 ? atoi(argv[3]) : 1);
  }
  if (argc < 6) {
    fprintf(stderr,
            "usage: tgen_like <srv-base> <srv-count> <port> <streams> "
            "<bytes>\n");
    return 2;
  }
  const char* base = argv[1];
  int nsrv = atoi(argv[2]);
  const char* port = argv[3];
  int streams = atoi(argv[4]);
  long long nbytes = atoll(argv[5]);

  // deterministic server choice from the SIMULATED hostname
  char hn[128] = {0};
  gethostname(hn, sizeof(hn) - 1);
  unsigned h = 2166136261u;
  for (char* p = hn; *p; p++) h = (h ^ (unsigned char)*p) * 16777619u;
  char srv[160];
  snprintf(srv, sizeof(srv), "%s%u", base, 1 + h % (unsigned)nsrv);

  struct addrinfo hints, *res = 0;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(srv, port, &hints, &res) != 0 || !res) {
    fprintf(stderr, "resolve %s failed\n", srv);
    return 1;
  }
  char* buf = malloc(65536);
  int ok = 0;
  for (int i = 0; i < streams; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      perror("connect");
      close(fd);
      continue;
    }
    char req[64];
    int n = snprintf(req, sizeof(req), "SEND %lld\n", nbytes);
    if (send(fd, req, n, 0) != n) {
      close(fd);
      continue;
    }
    if (read_n(fd, buf, nbytes) == 0) {
      printf("stream-success %d %lld at %lld\n", i, nbytes, now_ns());
      ok++;
    } else {
      printf("stream-error %d\n", i);
    }
    close(fd);
  }
  printf("transfers-complete %d\n", ok);
  free(buf);
  freeaddrinfo(res);
  return ok == streams ? 0 : 1;
}
