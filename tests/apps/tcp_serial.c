/* Makes <count> sequential TCP connections, sending <bytes> on each and
 * reading the peer's close before the next. Exercises connection slot
 * recycling. Usage: tcp_serial <server> <port> <count> <bytes> */
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char** argv) {
  const char* server = argc > 1 ? argv[1] : "server";
  const char* port = argc > 2 ? argv[2] : "9001";
  int count = argc > 3 ? atoi(argv[3]) : 6;
  long long nbytes = argc > 4 ? atoll(argv[4]) : 4000;

  struct addrinfo hints, *res = NULL;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(server, port, &hints, &res) != 0 || !res) {
    fprintf(stderr, "resolve failed\n");
    return 1;
  }
  char buf[4096];
  memset(buf, 'y', sizeof(buf));
  for (int i = 0; i < count; i++) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) { perror("socket"); return 1; }
    if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
      perror("connect");
      return 1;
    }
    long long sent = 0;
    while (sent < nbytes) {
      size_t chunk = sizeof(buf);
      if ((long long)chunk > nbytes - sent) chunk = (size_t)(nbytes - sent);
      ssize_t n = send(fd, buf, chunk, 0);
      if (n <= 0) { perror("send"); return 1; }
      sent += n;
    }
    shutdown(fd, SHUT_WR);
    /* wait for the peer to drain + close so the connection fully finishes
     * (client enters TIME_WAIT) before the next round */
    ssize_t n = recv(fd, buf, sizeof(buf), 0);
    if (n != 0) { fprintf(stderr, "conn %d: expected EOF\n", i); return 1; }
    close(fd);
    printf("conn %d done\n", i);
  }
  printf("all %d connections done\n", count);
  freeaddrinfo(res);
  return 0;
}
