/* Virtual CPU visibility probe: under the simulator both the raw
 * sched_getaffinity mask and glibc's sysconf(_SC_NPROCESSORS_ONLN)
 * (which derives from it) must report the simulated host's CPU count,
 * not the real machine's. */
#define _GNU_SOURCE
#include <sched.h>
#include <stdio.h>
#include <unistd.h>

int main(void) {
  cpu_set_t s;
  CPU_ZERO(&s);
  int r = sched_getaffinity(0, sizeof(s), &s);
  printf("affinity rc=%d count=%d\n", r < 0 ? -1 : 0, CPU_COUNT(&s));
  printf("nproc %ld\n", sysconf(_SC_NPROCESSORS_ONLN));
  return 0;
}
