/* Helper exec'd by exec_parent: proves a fork+exec'd image stays managed —
 * its fresh shim attaches on the inherited channel, so the clock it reads
 * is the VIRTUAL clock and its UDP datagram rides the simulated network. */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

int main(int argc, char** argv) {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  printf("exec_child t %lld\n",
         (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec);
  int port = argc > 1 ? atoi(argv[1]) : 7200;
  int s = socket(AF_INET, SOCK_DGRAM, 0);
  struct sockaddr_in dst;
  memset(&dst, 0, sizeof(dst));
  dst.sin_family = AF_INET;
  dst.sin_addr.s_addr = htonl(0x7F000001);
  dst.sin_port = htons(port);
  const char* msg = "hello from exec";
  sendto(s, msg, strlen(msg), 0, (struct sockaddr*)&dst, sizeof(dst));
  return 0;
}
