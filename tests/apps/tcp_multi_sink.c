/* Accepts <count> connections sequentially; reads each to EOF and closes.
 * Usage: tcp_multi_sink <port> <count> */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 9001;
  int count = argc > 2 ? atoi(argv[2]) : 6;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); return 1; }
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(fd, 8) != 0) { perror("listen"); return 1; }
  long long grand = 0;
  for (int i = 0; i < count; i++) {
    int cfd = accept(fd, NULL, NULL);
    if (cfd < 0) { perror("accept"); return 1; }
    char buf[8192];
    long long total = 0;
    for (;;) {
      ssize_t n = recv(cfd, buf, sizeof(buf), 0);
      if (n < 0) { perror("recv"); return 1; }
      if (n == 0) break;
      total += n;
    }
    close(cfd);
    grand += total;
    printf("conn %d received %lld\n", i, total);
  }
  printf("total %lld bytes over %d connections\n", grand, count);
  close(fd);
  return 0;
}
