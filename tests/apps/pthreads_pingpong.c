/* Multithreaded managed app: N worker threads pass a token around with a
 * mutex + condvar (interposed by the shim; contended waits park in the
 * driver), each holder sleeps 10ms on the VIRTUAL clock, and the main
 * thread joins everyone. Deterministic output: the token order is fixed by
 * the driver's one-thread-at-a-time scheduling, and the printed timestamps
 * are exact virtual-clock values.
 * Usage: pthreads_pingpong <nthreads> <rounds> */
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

static pthread_mutex_t lock = PTHREAD_MUTEX_INITIALIZER;
static pthread_cond_t cv = PTHREAD_COND_INITIALIZER;
static int token = 0;
static int nthreads = 3;
static int rounds = 2;

static long long now_ns(void) {
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  return (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
}

static void* worker(void* vp) {
  int id = (int)(long)vp;
  for (int r = 0; r < rounds; r++) {
    pthread_mutex_lock(&lock);
    while (token % nthreads != id) pthread_cond_wait(&cv, &lock);
    printf("t%d round %d at %lld\n", id, r, now_ns());
    struct timespec d = {0, 10000000};
    nanosleep(&d, 0);
    token++;
    pthread_cond_broadcast(&cv);
    pthread_mutex_unlock(&lock);
  }
  return (void*)(long)(id * 100);
}

int main(int argc, char** argv) {
  if (argc > 1) nthreads = atoi(argv[1]);
  if (argc > 2) rounds = atoi(argv[2]);
  pthread_t th[16];
  for (int i = 0; i < nthreads && i < 16; i++)
    pthread_create(&th[i], 0, worker, (void*)(long)i);
  long sum = 0;
  for (int i = 0; i < nthreads && i < 16; i++) {
    void* rv = 0;
    pthread_join(th[i], &rv);
    sum += (long)rv;
  }
  printf("joined sum %ld token %d at %lld\n", sum, token, now_ns());
  return 0;
}
