/* Signal-semantics probes for the virtual signal plane (one mode per run):
 *
 *   reenter    — a handler that re-raises its own signal must NOT nest:
 *                delivery auto-blocks the signo until the handler returns
 *                (Linux sigaction semantics); the second delivery runs
 *                after, so max observed depth stays 1.
 *   groupkill  — kill(0, SIGTERM) signals the fork lineage VIRTUALLY: the
 *                parent's handler runs, the handler-less child dies with
 *                the default disposition; a native escape would kill the
 *                test harness itself.
 *   dflpending — a signal left pending while blocked, then reset to
 *                SIG_DFL and unblocked, applies the CURRENT (default,
 *                terminating) disposition — the process must die.
 *
 * Reference analogs: syscall/signal.c, shim.c signal handling,
 * src/test/signal.
 */
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static volatile int depth = 0, maxdepth = 0, runs = 0;

static void msleep(long ms) {
  struct timespec ts = {ms / 1000, (ms % 1000) * 1000000L};
  nanosleep(&ts, NULL);
}

static void on_usr1(int sig) {
  (void)sig;
  depth++;
  if (depth > maxdepth) maxdepth = depth;
  runs++;
  if (runs == 1) raise(SIGUSR1); /* must defer, not nest */
  msleep(5);                     /* a syscall inside the handler: its reply
                                  * must not re-enter us with the same signo */
  depth--;
}

static void on_term(int sig) {
  (void)sig;
  const char m[] = "parent-term\n";
  write(1, m, sizeof(m) - 1);
}

int main(int argc, char** argv) {
  setvbuf(stdout, NULL, _IONBF, 0);
  const char* mode = argc > 1 ? argv[1] : "reenter";

  if (strcmp(mode, "reenter") == 0) {
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = on_usr1;
    sigaction(SIGUSR1, &sa, NULL);
    raise(SIGUSR1);
    msleep(50); /* syscall boundary so the deferred delivery lands */
    printf("runs=%d maxdepth=%d\n", runs, maxdepth);
    return 0;
  }

  if (strcmp(mode, "groupkill") == 0) {
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = on_term;
    sigaction(SIGTERM, &sa, NULL);
    pid_t pid = fork();
    if (pid == 0) {
      signal(SIGTERM, SIG_DFL); /* drop the inherited handler (POSIX: fork
                                 * inherits dispositions) */
      for (;;) msleep(100); /* default disposition kills us */
    }
    msleep(50);
    kill(0, SIGTERM); /* whole lineage, virtually */
    int st = 0;
    pid_t w = waitpid(pid, &st, 0);
    printf("child-signaled=%d sig=%d pid-match=%d\n", WIFSIGNALED(st),
           WIFSIGNALED(st) ? WTERMSIG(st) : 0, w == pid);
    return 0;
  }

  if (strcmp(mode, "dflpending") == 0) {
    struct sigaction sa;
    memset(&sa, 0, sizeof(sa));
    sa.sa_handler = on_term; /* handler exists at post time */
    sigaction(SIGUSR2, &sa, NULL);
    sigset_t s;
    sigemptyset(&s);
    sigaddset(&s, SIGUSR2);
    sigprocmask(SIG_BLOCK, &s, NULL);
    raise(SIGUSR2); /* pending (blocked) */
    signal(SIGUSR2, SIG_DFL);
    printf("about-to-unblock\n");
    sigprocmask(SIG_UNBLOCK, &s, NULL); /* default action: terminate */
    msleep(50);
    printf("survived\n"); /* must NOT print */
    return 0;
  }

  fprintf(stderr, "unknown mode %s\n", mode);
  return 2;
}
