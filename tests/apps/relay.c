/* Onion-style chained TCP forwarder (the tor-relay analog for the
 * multi-hop e2e, reference: src/test/tor/minimal). Protocol: each inbound
 * connection starts with one header line
 *     hop1:port1/hop2:port2/.../\n
 * naming the REMAINING circuit hops. The relay strips the first hop,
 * connects to it, forwards the shortened header, then splices bytes both
 * ways until EOF. A single poll() loop multiplexes many circuits.
 *
 * Usage: relay <listen_port> [max_lifetime_s]
 */
#define _GNU_SOURCE
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <poll.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define MAX_SESS 512
#define BUF 4096

typedef struct {
  int up;    /* inbound (toward client) */
  int down;  /* outbound (toward next hop); -1 until connected */
  int connecting; /* nonblocking connect in flight on down */
  char hdr[512];
  int hdr_len;
  int hdr_done;
  /* pending bytes parked in either direction */
  char ub[BUF];
  int ub_n;
  char db[BUF];
  int db_n;
  int up_eof, down_eof;
  char fwd_hdr[512];
  int fwd_len, fwd_sent;
} Sess;

static Sess sess[MAX_SESS];
static int nsess = 0;

/* NONBLOCKING connect (a blocking one would serialize every circuit
 * through this relay on the network RTT — the scale wall a real relay
 * avoids the same way). *connecting is set when completion is pending
 * (POLLOUT + SO_ERROR check). */
static int conn_to(const char* host, int port, int* connecting) {
  struct addrinfo hints = {0}, *ai = NULL;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char ps[16];
  snprintf(ps, sizeof ps, "%d", port);
  if (getaddrinfo(host, ps, &hints, &ai) != 0 || !ai) return -1;
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    freeaddrinfo(ai);
    return -1;
  }
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
  *connecting = 0;
  if (connect(fd, ai->ai_addr, ai->ai_addrlen) != 0) {
    if (errno == EINPROGRESS) {
      *connecting = 1;
    } else {
      close(fd);
      freeaddrinfo(ai);
      return -1;
    }
  }
  freeaddrinfo(ai);
  return fd;
}

static int would_block(ssize_t r) {
  return r < 0 && (errno == EAGAIN || errno == EWOULDBLOCK);
}

static void drop(int i) {
  if (sess[i].up >= 0) close(sess[i].up);
  if (sess[i].down >= 0) close(sess[i].down);
  sess[i] = sess[--nsess];
}

int main(int argc, char** argv) {
  if (argc < 2) return 2;
  int port = atoi(argv[1]);
  int life = argc > 2 ? atoi(argv[2]) : 0;
  time_t t0 = time(NULL);
  int ls = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(ls, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in a = {0};
  a.sin_family = AF_INET;
  a.sin_port = htons(port);
  if (bind(ls, (struct sockaddr*)&a, sizeof a) != 0) {
    perror("bind");
    return 1;
  }
  listen(ls, 256);
  fprintf(stdout, "relay up %d\n", port);
  fflush(stdout);

  for (;;) {
    if (life && time(NULL) - t0 >= life) break;
    struct pollfd pf[1 + 2 * MAX_SESS];
    int map[1 + 2 * MAX_SESS];
    int n = 0;
    pf[n].fd = ls;
    pf[n].events = nsess < MAX_SESS ? POLLIN : 0;
    map[n++] = -1;
    for (int i = 0; i < nsess; i++) {
      Sess* s = &sess[i];
      short ue = 0, de = 0;
      if (!s->hdr_done || (!s->up_eof && s->ub_n < BUF)) ue |= POLLIN;
      if (s->db_n > 0) ue |= POLLOUT;
      if (s->down >= 0) {
        if (s->connecting) {
          de = POLLOUT;  /* connect completion only */
        } else {
          if (s->fwd_sent < s->fwd_len || s->ub_n > 0) de |= POLLOUT;
          if (!s->down_eof && s->db_n < BUF) de |= POLLIN;
        }
      }
      pf[n].fd = s->up;
      pf[n].events = ue;
      map[n++] = i;
      if (s->down >= 0) {
        pf[n].fd = s->down;
        pf[n].events = de;
        map[n++] = i;
      }
    }
    int rc = poll(pf, n, 1000);
    if (rc < 0) break;
    if (pf[0].revents & POLLIN) {
      int c = accept(ls, NULL, NULL);
      if (c >= 0 && nsess < MAX_SESS) {
        fcntl(c, F_SETFL, fcntl(c, F_GETFL, 0) | O_NONBLOCK);
        Sess* s = &sess[nsess++];
        memset(s, 0, sizeof *s);
        s->up = c;
        s->down = -1;
      } else if (c >= 0) {
        close(c);
      }
    }
    for (int k = 1; k < n; k++) {
      int i = map[k];
      if (i >= nsess) continue;  /* compacted away this round */
      Sess* s = &sess[i];
      int fd = pf[k].fd;
      if (fd != s->up && fd != s->down) continue;
      short re = pf[k].revents;
      if (!re) continue;
      if (fd == s->up && !s->hdr_done && (re & (POLLIN | POLLHUP))) {
        ssize_t r = read(s->up, s->hdr + s->hdr_len,
                         sizeof(s->hdr) - 1 - s->hdr_len);
        if (would_block(r)) continue;
        if (r <= 0) {
          drop(i);
          continue;
        }
        s->hdr_len += (int)r;
        s->hdr[s->hdr_len] = 0;
        char* nl = strchr(s->hdr, '\n');
        if (!nl) continue;
        *nl = 0;
        /* first hop = "host:port"; rest (may be empty) forwards on */
        char* slash = strchr(s->hdr, '/');
        char rest[512] = "";
        if (slash) {
          snprintf(rest, sizeof rest, "%s", slash + 1);
          *slash = 0;
        }
        char* colon = strchr(s->hdr, ':');
        if (!colon) {
          drop(i);
          continue;
        }
        *colon = 0;
        int dport = atoi(colon + 1);
        s->down = conn_to(s->hdr, dport, &s->connecting);
        if (s->down < 0) {
          drop(i);
          continue;
        }
        if (rest[0]) {
          s->fwd_len = snprintf(s->fwd_hdr, sizeof s->fwd_hdr, "%s\n", rest);
        }
        /* any app bytes that followed the newline are queued upstream */
        int extra = s->hdr_len - (int)(nl - s->hdr) - 1;
        if (extra > 0) {
          memcpy(s->ub, nl + 1, (size_t)extra);
          s->ub_n = extra;
        }
        s->hdr_done = 1;
        continue;
      }
      if (fd == s->down && s->connecting && (re & (POLLOUT | POLLERR))) {
        int err = 0;
        socklen_t el = sizeof err;
        getsockopt(s->down, SOL_SOCKET, SO_ERROR, &err, &el);
        if (err != 0) {
          drop(i);
          continue;
        }
        s->connecting = 0;
      }
      if (fd == s->down && !s->connecting && (re & POLLOUT)) {
        if (s->fwd_sent < s->fwd_len) {
          ssize_t w = write(s->down, s->fwd_hdr + s->fwd_sent,
                            (size_t)(s->fwd_len - s->fwd_sent));
          if (w > 0) s->fwd_sent += (int)w;
        } else if (s->ub_n > 0) {
          ssize_t w = write(s->down, s->ub, (size_t)s->ub_n);
          if (w > 0) {
            memmove(s->ub, s->ub + w, (size_t)(s->ub_n - w));
            s->ub_n -= (int)w;
          }
        }
      }
      if (fd == s->up && s->hdr_done && (re & (POLLIN | POLLHUP))) {
        if (s->ub_n < BUF) {
          ssize_t r = read(s->up, s->ub + s->ub_n, (size_t)(BUF - s->ub_n));
          if (would_block(r)) {
            /* spurious wake: not EOF */
          } else if (r <= 0) {
            s->up_eof = 1;
            if (s->down >= 0 && s->ub_n == 0 && s->fwd_sent >= s->fwd_len &&
                !s->connecting)
              shutdown(s->down, SHUT_WR);
          } else {
            s->ub_n += (int)r;
          }
        }
      }
      if (fd == s->down && !s->connecting && (re & (POLLIN | POLLHUP))) {
        if (s->db_n < BUF) {
          ssize_t r = read(s->down, s->db + s->db_n,
                           (size_t)(BUF - s->db_n));
          if (would_block(r)) {
            r = 0; /* placeholder; handled below */
            goto down_read_done;
          }
          if (r <= 0) {
            s->down_eof = 1;
            if (s->db_n == 0) shutdown(s->up, SHUT_WR);
          } else {
            s->db_n += (int)r;
          }
        }
      }
      down_read_done:
      if (fd == s->up && (re & POLLOUT) && s->db_n > 0) {
        ssize_t w = write(s->up, s->db, (size_t)s->db_n);
        if (w > 0) {
          memmove(s->db, s->db + w, (size_t)(s->db_n - w));
          s->db_n -= (int)w;
          if (s->down_eof && s->db_n == 0) shutdown(s->up, SHUT_WR);
        }
      }
      /* drain completions */
      if (s->up_eof == 1 && s->down >= 0 && !s->connecting &&
          s->ub_n == 0 && s->fwd_sent >= s->fwd_len) {
        shutdown(s->down, SHUT_WR);
        s->up_eof = 2;
      }
      if (s->up_eof && s->down_eof && s->ub_n == 0 && s->db_n == 0) {
        drop(i);
      }
    }
  }
  fprintf(stdout, "relay done\n");
  return 0;
}
