/* libevent-style multi-process workload for the virtual signal +
 * AF_UNIX plane (VERDICT round-2 ask #4): the parent installs a SIGCHLD
 * handler that writes to a socketpair (the classic self-pipe trick),
 * listens on a NAMED unix socket, forks a child that connects to it and
 * sends a message, then event-loops with epoll over both fds, reaping
 * the child with waitpid when the handler fires. Every line of output is
 * deterministic under the driver's virtual clock.
 *
 * Reference analogs: syscall/signal.c (rt_sigaction/kill/SIGCHLD),
 * descriptor/channel.c + unix sockets, src/test/signal + src/test/clone.
 */
#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

static int sv[2];

static void on_sigchld(int sig) {
  char b = 'S';
  (void)sig;
  write(sv[1], &b, 1);
}

static void msleep(long ms) {
  struct timespec ts = {ms / 1000, (ms % 1000) * 1000000L};
  nanosleep(&ts, NULL);
}

int main(void) {
  setvbuf(stdout, NULL, _IONBF, 0);
  if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
    perror("socketpair");
    return 1;
  }

  struct sockaddr_un sun;
  memset(&sun, 0, sizeof(sun));
  sun.sun_family = AF_UNIX;
  strcpy(sun.sun_path, "u.sock");
  int lfd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (bind(lfd, (struct sockaddr*)&sun, sizeof(sun)) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(lfd, 4) != 0) {
    perror("listen");
    return 1;
  }

  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_handler = on_sigchld;
  if (sigaction(SIGCHLD, &sa, NULL) != 0) {
    perror("sigaction");
    return 1;
  }

  pid_t pid = fork();
  if (pid == 0) {
    /* child: connect to the named socket, send, exit 7 */
    msleep(50);
    int c = socket(AF_UNIX, SOCK_STREAM, 0);
    if (connect(c, (struct sockaddr*)&sun, sizeof(sun)) != 0) {
      perror("child connect");
      _exit(2);
    }
    send(c, "hello-unix", 10, 0);
    close(c);
    msleep(50);
    _exit(7);
  }

  int ep = epoll_create1(0);
  struct epoll_event ev;
  ev.events = EPOLLIN;
  ev.data.fd = sv[0];
  epoll_ctl(ep, EPOLL_CTL_ADD, sv[0], &ev);
  ev.data.fd = lfd;
  epoll_ctl(ep, EPOLL_CTL_ADD, lfd, &ev);

  int reaped = 0, got = 0;
  while (!reaped || !got) {
    struct epoll_event out[4];
    int n = epoll_wait(ep, out, 4, 5000);
    if (n < 0) {
      if (errno == EINTR) continue; /* SIGCHLD handler ran */
      perror("epoll_wait");
      return 1;
    }
    for (int i = 0; i < n; i++) {
      if (out[i].data.fd == lfd) {
        int c = accept(lfd, NULL, NULL);
        char buf[64];
        ssize_t r = recv(c, buf, sizeof(buf) - 1, 0);
        if (r < 0) r = 0;
        buf[r] = 0;
        printf("got: %s\n", buf);
        got = 1;
        close(c);
      } else if (out[i].data.fd == sv[0]) {
        char b;
        read(sv[0], &b, 1);
        int st = 0;
        pid_t w = waitpid(-1, &st, 0);
        printf("reaped: pid-match=%d status=%d\n", w == pid,
               WIFEXITED(st) ? WEXITSTATUS(st) : -1);
        reaped = 1;
      }
    }
  }
  printf("done\n");
  return 0;
}
