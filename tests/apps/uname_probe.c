/* uname.nodename must agree with gethostname under the simulator. */
#include <stdio.h>
#include <string.h>
#include <sys/utsname.h>
#include <unistd.h>

int main(void) {
  struct utsname u;
  char hn[256];
  if (uname(&u) != 0) return 1;
  if (gethostname(hn, sizeof(hn)) != 0) return 1;
  printf("match %d nodename=%s\n", strcmp(u.nodename, hn) == 0, u.nodename);
  return 0;
}
