/* Minimal UDP echo server: binds PORT, echoes N datagrams, exits.
 * Run as a REAL process under the shadow_tpu shim (dual-target: also runs
 * natively). Usage: udp_echo_server <port> <count> */
#include <arpa/inet.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 9000;
  int count = argc > 2 ? atoi(argv[2]) : 1;
  int fd = socket(AF_INET, SOCK_DGRAM, 0);
  if (fd < 0) { perror("socket"); return 1; }
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(port);
  if (bind(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    perror("bind");
    return 1;
  }
  char buf[2048];
  for (int i = 0; i < count; i++) {
    struct sockaddr_in src;
    socklen_t slen = sizeof(src);
    ssize_t n = recvfrom(fd, buf, sizeof(buf), 0, (struct sockaddr*)&src, &slen);
    if (n < 0) { perror("recvfrom"); return 1; }
    if (sendto(fd, buf, n, 0, (struct sockaddr*)&src, slen) != n) {
      perror("sendto");
      return 1;
    }
    printf("echoed %zd bytes\n", n);
  }
  close(fd);
  printf("server done\n");
  return 0;
}
