/* TCP source: connects to <server>:<port>, sends <bytes> bytes, closes.
 * Usage: tcp_source <server> <port> <bytes> */
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

int main(int argc, char** argv) {
  const char* server = argc > 1 ? argv[1] : "server";
  const char* port = argc > 2 ? argv[2] : "9001";
  long long total = argc > 3 ? atoll(argv[3]) : 65536;

  struct addrinfo hints, *res = NULL;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(server, port, &hints, &res) != 0 || !res) {
    fprintf(stderr, "resolve failed\n");
    return 1;
  }
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { perror("socket"); return 1; }
  if (connect(fd, res->ai_addr, res->ai_addrlen) != 0) {
    perror("connect");
    return 1;
  }
  char buf[8192];
  memset(buf, 'x', sizeof(buf));
  long long sent = 0;
  while (sent < total) {
    size_t chunk = sizeof(buf);
    if ((long long)chunk > total - sent) chunk = (size_t)(total - sent);
    ssize_t n = send(fd, buf, chunk, 0);
    if (n <= 0) { perror("send"); return 1; }
    sent += n;
  }
  printf("sent %lld bytes\n", sent);
  close(fd);
  freeaddrinfo(res);
  return 0;
}
