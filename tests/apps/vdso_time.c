/* Calls clock_gettime THROUGH THE vDSO ENTRY POINT directly — the one time
 * path neither libc interposition nor seccomp can see (vDSO calls never
 * enter the kernel). The shim neutralizes it at init by patching the vDSO
 * entry points into real syscall instructions; this program proves that by
 * resolving __vdso_clock_gettime from the auxv ELF image and calling it.
 * With the patch the printed value is the virtual clock (= process start
 * time); without it, real wall-clock epoch time.
 * Prints: "vdso t0 <ns>" and "vdso dt <ns>" (after a 100ms nanosleep). */
#define _GNU_SOURCE
#include <elf.h>
#include <stdio.h>
#include <string.h>
#include <sys/auxv.h>
#include <time.h>

typedef int (*cg_fn)(clockid_t, struct timespec*);

static cg_fn find_vdso_clock_gettime(void) {
  unsigned long base = getauxval(AT_SYSINFO_EHDR);
  if (!base) return 0;
  const Elf64_Ehdr* eh = (const Elf64_Ehdr*)base;
  const Elf64_Phdr* ph = (const Elf64_Phdr*)(base + eh->e_phoff);
  unsigned long dyn = 0, load = (unsigned long)-1;
  for (int i = 0; i < eh->e_phnum; i++) {
    if (ph[i].p_type == PT_DYNAMIC) dyn = ph[i].p_vaddr;
    if (ph[i].p_type == PT_LOAD && ph[i].p_vaddr < load) load = ph[i].p_vaddr;
  }
  if (!dyn || load == (unsigned long)-1) return 0;
  unsigned long slide = base - load;
  const Elf64_Sym* symtab = 0;
  const char* strtab = 0;
  for (const Elf64_Dyn* d = (const Elf64_Dyn*)(slide + dyn);
       d->d_tag != DT_NULL; d++) {
    unsigned long p = (unsigned long)d->d_un.d_ptr;
    if (p < base) p += slide;
    if (d->d_tag == DT_SYMTAB) symtab = (const Elf64_Sym*)p;
    if (d->d_tag == DT_STRTAB) strtab = (const char*)p;
  }
  if (!symtab || !strtab || (unsigned long)strtab <= (unsigned long)symtab)
    return 0;
  unsigned long n = ((unsigned long)strtab - (unsigned long)symtab) /
                    sizeof(Elf64_Sym);
  for (unsigned long s = 0; s < n && s < 4096; s++) {
    if (!symtab[s].st_value || !symtab[s].st_name) continue;
    const char* nm = strtab + symtab[s].st_name;
    if (strcmp(nm, "__vdso_clock_gettime") == 0 ||
        strcmp(nm, "clock_gettime") == 0)
      return (cg_fn)(slide + symtab[s].st_value);
  }
  return 0;
}

int main(void) {
  cg_fn vcg = find_vdso_clock_gettime();
  if (!vcg) {
    printf("vdso absent\n");
    return 2;
  }
  struct timespec ts;
  if (vcg(CLOCK_REALTIME, &ts) != 0) {
    printf("vdso call failed\n");
    return 3;
  }
  long long t0 = (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
  printf("vdso t0 %lld\n", t0);
  struct timespec req = {0, 100000000};
  nanosleep(&req, 0);
  vcg(CLOCK_REALTIME, &ts);
  long long t1 = (long long)ts.tv_sec * 1000000000LL + ts.tv_nsec;
  printf("vdso dt %lld\n", t1 - t0);
  return 0;
}
