"""Elastic mesh resilience (ISSUE 13): survive chip loss by drain →
relayout → resume on the surviving mesh, with health probing and
re-expansion (parallel/elastic.py, core/supervisor.py policy
`relayout`).

The acceptance surface: kill_chip × {async mesh, fleet-on-mesh} ×
{relayout, wait + re-expand, abort} all chain-identical to
uninterrupted runs; SIGKILL during a relayout resumes cleanly from the
drain checkpoint; flapping-chip hysteresis holds (no relayout storm);
the shrink-to-1 arm resumes on the GLOBAL engine; drain checkpoints
live in their own `drain-*` ring namespace (the periodic ring never
rotates for them); metrics schema v12 validated and absent on non-mesh
runs. Chips here are vmap-virtual (relayout is a partition property,
not a device property — test_mesh.py and --mesh-resilience-smoke cover
shard_map); probes and sleeps are instantaneous injections, so only
wall scheduling is perturbed — which is exactly the property under
test."""

import os

import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.core import checkpoint as ckpt_mod
from shadow_tpu.core.supervisor import BackendLost, BackendSupervisor, ChipLost
from shadow_tpu.faults import plan as plan_mod
from shadow_tpu.parallel import elastic as elastic_mod
from shadow_tpu.parallel.islands import IslandSimulation
from shadow_tpu.sim import build_simulation

pytestmark = pytest.mark.quick


def _cfg(n=12, shards=4, stop=3, seed=11):
    hosts = {
        f"h{v:02d}": {
            "quantity": 1, "app_model": "phold",
            "app_options": {"msgload": 1, "runtime": stop - 1},
        }
        for v in range(n)
    }
    c = {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "experimental": {"event_capacity": 1024,
                         "events_per_host_per_window": 8},
        "hosts": hosts,
    }
    if shards > 1:
        c["experimental"].update(
            {"num_shards": shards, "exchange_slots": 16}
        )
    return c


def _quiet_sup(policy, **kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("probe_budget_s", 30.0)
    return BackendSupervisor(policy, **kw)


def _runner(base, td, *, faults, chips=4, **kw):
    kw.setdefault("supervisor", _quiet_sup("relayout"))
    kw.setdefault("probe_every", 1)
    kw.setdefault("hysteresis", 2)
    kw.setdefault("cooldown", 1)
    kw.setdefault("windows_per_dispatch", 8)
    return elastic_mod.ElasticMeshRunner(
        elastic_mod.config_builder(base), chips=chips, ckpt_dir=str(td),
        faults=plan_mod.parse_fault_plan(faults), **kw,
    )


_BASE = _cfg()


@pytest.fixture(scope="module")
def baseline():
    sim = build_simulation(_BASE)
    sim.run()
    return sim.audit_chain(), sim.counters()["events_committed"]


# ---------------------------------------------------------------------------
# chaos matrix: kill_chip × async mesh × {relayout, wait+re-expand, abort}
# ---------------------------------------------------------------------------


def test_kill_chip_relayout_degraded_finish(baseline, tmp_path):
    """Chip stays down: drain → relayout 4→3 → finish degraded, chain
    and committed events bit-identical to the uninterrupted run."""
    chain, events = baseline
    r = _runner(_BASE, tmp_path, faults=[
        {"at": "1 s", "op": "kill_chip", "chip": 2}  # never recovers
    ])
    sim = r.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert isinstance(sim, IslandSimulation) and sim.num_shards == 3
    assert r.counters["relayouts"] == 1
    assert r.counters["re_expansions"] == 0
    assert r.chips_up == 3
    assert r.supervisor.counters["chip_losses"] == 1


def test_kill_chip_relayout_then_reexpand(baseline, tmp_path):
    """The chip answers probes again: drain → relayout 4→3 → probe
    hysteresis → re-expand 3→4 at a dispatch boundary — chain identical,
    one counted kernel rebuild per mesh change. A multi-tier gear
    ladder rides along: each relayout restores an S_old-width
    `gear_levels` header onto an S_new build, so the ShardGearShifter
    re-seeds flat across the resize (gearbox.restore's width rule) —
    still chain-exact."""
    chain, events = baseline
    base = dict(_BASE, experimental={
        **_BASE["experimental"], "pool_gears": 2,
    })
    r = _runner(base, tmp_path, faults=[
        {"at": "1 s", "op": "kill_chip", "chip": 2, "recover_after": 2}
    ])
    sim = r.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert sim.num_shards == 4 and r.chips_up == 4
    assert r.counters["relayouts"] == 1
    assert r.counters["re_expansions"] == 1
    # exactly one fresh kernel set per mesh change (+ the initial build)
    assert r.counters["kernel_rebuilds"] == 3
    assert r.last_relayout["reason"].startswith("re_expand:")
    # the per-shard shifter really did rebuild at the new width
    assert sim._shard_shifter is not None
    assert len(sim._shard_shifter.levels) == 4


def test_kill_chip_wait_hot_resume(baseline):
    """Policy `wait` control arm: the whole mesh holds until the chip
    answers, then hot-resumes in place — no relayout, chain identical."""
    chain, events = baseline
    sim = build_simulation(_BASE)
    sup = _quiet_sup("wait")
    sim.attach_supervisor(sup)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_chip", "chip": 1, "recover_after": 2}]
    ))
    sim.run(windows_per_dispatch=8)
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert sup.counters["hot_resumes"] == 1
    assert sup.counters["chip_losses"] == 1
    assert not sup.chips_down


def test_kill_chip_abort_drains_then_resumes(baseline, tmp_path):
    """Policy `abort`: the drain lands in the drain-* namespace, the
    raise is resumable, and resume_from (which walks BOTH ring
    namespaces) finishes bit-identically."""
    chain, events = baseline
    sim = build_simulation(_BASE)
    sim.checkpoint_dir = str(tmp_path)
    sim.attach_supervisor(_quiet_sup("abort"))
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_chip", "chip": 0}]
    ))
    with pytest.raises(BackendLost, match="drained to"):
        sim.run(windows_per_dispatch=8)
    names = os.listdir(tmp_path)
    assert any(x.startswith("drain-") for x in names)
    assert not any(x.startswith("ckpt-") for x in names)

    resumed = build_simulation(_BASE)
    info = resumed.resume_from(str(tmp_path))
    assert info["fallbacks"] == 0
    resumed.run()
    assert resumed.audit_chain() == chain
    assert resumed.counters()["events_committed"] == events


def test_chip_lost_carries_dead_set(tmp_path):
    """Policy `relayout` without a runner: ChipLost (a BackendLost
    subclass) surfaces the dead chip set + drain path to the caller."""
    sim = build_simulation(_BASE)
    sim.checkpoint_dir = str(tmp_path)
    sim.attach_supervisor(_quiet_sup("relayout"))
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_chip", "chip": 2}]
    ))
    with pytest.raises(ChipLost) as e:
        sim.run(windows_per_dispatch=8)
    assert e.value.chips == {2}
    assert e.value.path and os.path.basename(e.value.path).startswith(
        "drain-"
    )
    # the survivors are healthy: the supervisor cleared its dead flag
    # (the elastic runner re-binds it to the rebuilt sim)
    assert not sim.supervisor.degraded
    assert sim.supervisor.chips_down == {2}


# ---------------------------------------------------------------------------
# S→1 endpoint + SIGKILL-mid-relayout + flapping hysteresis
# ---------------------------------------------------------------------------


def test_shrink_to_one_falls_back_to_global_engine(tmp_path):
    """2 chips losing one leaves no mesh to shard over: the run resumes
    on the GLOBAL engine (islands.globalize_state), chain-identical."""
    base = _cfg(n=6, shards=2, seed=7)
    ref = build_simulation(base)
    ref.run()
    r = _runner(base, tmp_path, chips=2, faults=[
        {"at": "1 s", "op": "kill_chip", "chip": 1}
    ])
    sim = r.run()
    assert not isinstance(sim, IslandSimulation)
    assert sim.audit_chain() == ref.audit_chain()
    assert (sim.counters()["events_committed"]
            == ref.counters()["events_committed"])
    assert r.counters["relayouts"] == 1


def test_sigkill_during_relayout_resumes_from_drain(baseline, tmp_path):
    """The process dies between the drain and the rebuilt mesh's first
    dispatch: a fresh runner (fresh process semantics — nothing shared
    but the checkpoint directory and the plan) resumes from the drain
    checkpoint and finishes bit-identically, without re-firing the
    already-fired kill_chip."""
    chain, events = baseline
    faults = [{"at": "1 s", "op": "kill_chip", "chip": 2}]
    sim = build_simulation(_BASE)
    sim.configure_auto_checkpoint(str(tmp_path), 0)
    sim.attach_supervisor(_quiet_sup("relayout"))
    sim.attach_faults(plan_mod.parse_fault_plan(faults))
    with pytest.raises(ChipLost):
        sim.run(windows_per_dispatch=8)  # "SIGKILL" lands here
    del sim

    r2 = _runner(_BASE, tmp_path, faults=faults)
    r2.down = {2}  # the restarting operator knows the chip is dead
    r2.supervisor.mark_chip_down(2)
    r2.resume()
    sim2 = r2.run()
    assert sim2.audit_chain() == chain
    assert sim2.counters()["events_committed"] == events
    assert sim2.num_shards == 3  # finished degraded; chip never probed up


def test_flapping_chip_hysteresis_no_relayout_storm(baseline, tmp_path):
    """A chip that answers every other probe can NEVER re-expand: the
    hysteresis streak resets on each miss, so the run finishes degraded
    with exactly one relayout — no storm."""
    chain, events = baseline
    flip = {"n": 0}

    def flapping_probe():
        flip["n"] += 1
        return flip["n"] % 2 == 0

    sup = _quiet_sup("relayout", probe_fn=flapping_probe)
    r = _runner(_BASE, tmp_path, supervisor=sup, hysteresis=3, faults=[
        # recovers instantly as far as the injection is concerned; the
        # flapping probe_fn then governs the re-expansion streak
        {"at": "1 s", "op": "kill_chip", "chip": 2, "recover_after": 0}
    ])
    sim = r.run()
    assert sim.audit_chain() == chain
    assert sim.counters()["events_committed"] == events
    assert r.counters["relayouts"] == 1
    assert r.counters["re_expansions"] == 0  # the streak never held
    assert flip["n"] >= 3  # the prober really was consulted repeatedly


def test_drain_burst_never_rotates_periodic_ring(tmp_path):
    """ISSUE 13 satellite: N successive drains leave the periodic ring
    intact — drains rotate only against other drains."""
    sim = build_simulation(_cfg(shards=1))
    sim.configure_auto_checkpoint(str(tmp_path), 0, retain=2)
    # two periodic entries
    ckpt_mod.save_ring(sim, str(tmp_path), 0, 100, retain=2)
    ckpt_mod.save_ring(sim, str(tmp_path), 1, 200, retain=2)
    periodic = {e[2] for e in ckpt_mod.ring_entries(str(tmp_path),
                                                    prefix="ckpt")}
    assert len(periodic) == 2
    # a burst of drains, rotating through the drain namespace
    sim._ckpt_seq = 2
    for _ in range(5):
        path = sim._drain_to_checkpoint("chip_lost:test")
        assert os.path.basename(path).startswith("drain-")
    drains = ckpt_mod.ring_entries(str(tmp_path), prefix="drain")
    assert len(drains) == sim.checkpoint_retain  # drains rotated drains
    still = {e[2] for e in ckpt_mod.ring_entries(str(tmp_path),
                                                 prefix="ckpt")}
    assert still == periodic  # the periodic ring never lost an entry
    # and the newest entry overall (what resume picks first) is a drain
    merged = ckpt_mod.ring_entries(str(tmp_path))
    assert os.path.basename(merged[-1][2]).startswith("drain-")


# ---------------------------------------------------------------------------
# fleet-on-mesh: kill_chip drains + requeues; resume on the shrunk mesh
# ---------------------------------------------------------------------------


def _fleet_job_cfg(seed, stop_s):
    # only data-plane fields (seed, stop_time) vary across jobs:
    # app runtime is kernel-shaping, so it stays fixed fleet-wide
    c = _cfg(n=6, shards=2, stop=2, seed=seed)
    c["general"]["stop_time"] = stop_s
    for h in c["hosts"].values():
        h["app_options"]["runtime"] = 1
    return c


@pytest.fixture(scope="module")
def fleet_solo_chains():
    chains = []
    for i in range(2):
        s = build_simulation(_fleet_job_cfg(100 + i, 2 + i))
        s.run()
        chains.append(s.audit_chain())
    return chains


def test_fleet_kill_chip_requeue_and_resume_shrunk(
    fleet_solo_chains, tmp_path
):
    """Fleet-on-mesh leg: a fleet-level kill_chip under policy
    `relayout` drains every lane's slice, requeues the in-flight jobs
    (lane requeue on shrink), and raises ChipLost; `resume_fleet
    (num_shards=1)` rebuilds the sweep on the shrunk partition and
    every job's chain still equals its solo run — the slices re-layout
    through restore_relayout."""
    from shadow_tpu.fleet import JobSpec, build_fleet, resume_fleet

    fleet = build_fleet(
        [JobSpec(name=f"j{i}", config=_fleet_job_cfg(100 + i, 2 + i))
         for i in range(2)],
        lanes=2, checkpoint_dir=str(tmp_path),
    )
    fleet.attach_supervisor(_quiet_sup("relayout"))
    fleet.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_chip", "chip": 1}]
    ))
    with pytest.raises(ChipLost) as e:
        fleet.run()
    assert e.value.chips == {1}
    assert fleet._admission_paused
    assert fleet.sched.jobs_requeued >= 1  # lane requeue on shrink

    resumed = resume_fleet(str(tmp_path), num_shards=1)
    resumed.run()
    assert resumed.ok()
    by_name = {r.name: r.audit.get("chain")
               for r in resumed.sched.records}
    for i in range(2):
        assert by_name[f"j{i}"] == fleet_solo_chains[i], f"j{i}"


def test_fleet_kill_chip_abort_resume_same_mesh(
    fleet_solo_chains, tmp_path
):
    """Fleet-on-mesh + policy abort: kill_chip drains + requeues like
    any backend loss; `sweep --resume` semantics finish the sweep on
    the SAME mesh with solo chains — the no-relayout control cell."""
    from shadow_tpu.fleet import JobSpec, build_fleet, resume_fleet

    fleet = build_fleet(
        [JobSpec(name=f"j{i}", config=_fleet_job_cfg(100 + i, 2 + i))
         for i in range(2)],
        lanes=2, checkpoint_dir=str(tmp_path),
    )
    fleet.attach_supervisor(_quiet_sup("abort"))
    fleet.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_chip", "chip": 0}]
    ))
    with pytest.raises(BackendLost):
        fleet.run()
    resumed = resume_fleet(str(tmp_path))
    resumed.run()
    assert resumed.ok()
    by_name = {r.name: r.audit.get("chain")
               for r in resumed.sched.records}
    for i in range(2):
        assert by_name[f"j{i}"] == fleet_solo_chains[i], f"j{i}"


def test_fleet_kill_chip_wait_recovers_in_process(fleet_solo_chains):
    """Fleet-on-mesh + policy wait: the sweep holds until the chip
    answers, then continues in place — chains equal solo."""
    from shadow_tpu.fleet import JobSpec, build_fleet

    fleet = build_fleet(
        [JobSpec(name=f"j{i}", config=_fleet_job_cfg(100 + i, 2 + i))
         for i in range(2)],
        lanes=2,
    )
    sup = _quiet_sup("wait")
    fleet.attach_supervisor(sup)
    fleet.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_chip", "chip": 0, "recover_after": 2}]
    ))
    fleet.run()
    assert fleet.ok()
    assert sup.counters["hot_resumes"] == 1
    assert sup.counters["chip_losses"] == 1
    by_name = {r.name: r.audit.get("chain") for r in fleet.sched.records}
    for i in range(2):
        assert by_name[f"j{i}"] == fleet_solo_chains[i], f"j{i}"


def test_fleet_check_compat_refuses_mixed_partition():
    """After a relayout every swap-in must be rebuilt for the surviving
    mesh: _check_compat refuses a job built at the old shard count."""
    from shadow_tpu.fleet import FleetError, JobSpec, build_fleet

    fleet = build_fleet(
        [JobSpec(name="a", config=_fleet_job_cfg(1, 2))], lanes=1,
    )
    other = build_simulation(_cfg(n=6, shards=3, stop=2, seed=2))
    with pytest.raises(FleetError, match="mesh partition"):
        fleet._check_compat(other)


# ---------------------------------------------------------------------------
# kill_chip plan validation + schema v12 telemetry
# ---------------------------------------------------------------------------


def test_kill_chip_plan_validation():
    good = {
        "kind": plan_mod.PLAN_KIND,
        "schema_version": plan_mod.PLAN_SCHEMA_VERSION,
        "faults": [
            {"at": "1 s", "op": "kill_chip", "chip": 3},
            {"at": "1 s", "op": "kill_chip", "chip": 0,
             "recover_after": 2},
        ],
    }
    plan_mod.validate_fault_plan_doc(good)
    faults = plan_mod.parse_fault_plan(good["faults"])
    assert faults[0].chip == 3 and faults[1].chip == 0
    assert faults[1].recover_after == 2
    assert all(f.op in plan_mod.BACKEND_OPS for f in faults)
    plan_mod.check_backend_ops(faults, mesh_size=8)
    with pytest.raises(plan_mod.FaultPlanError, match="out of range"):
        plan_mod.check_backend_ops(faults, mesh_size=3)
    for bad in (
        [{"at": 1, "op": "kill_chip"}],                      # chip required
        [{"at": 1, "op": "kill_chip", "chip": -1}],
        [{"at": 1, "op": "kill_chip", "chip": "x"}],
        [{"at": 1, "op": "kill_chip", "chip": 1,
          "recover_after": -1}],
        [{"at": 1, "op": "kill_chip", "chip": 1, "host": 2}],
    ):
        with pytest.raises(plan_mod.FaultPlanError):
            plan_mod.parse_fault_plan(bad)


def test_validate_fault_plan_cli_mesh_size(tmp_path, capsys):
    """tools/validate_fault_plan.py --mesh-size: clean nonzero exit on a
    chip index past the mesh, 0 on a valid plan."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    try:
        from tools.validate_fault_plan import main
    finally:
        sys.path.pop(0)
    import json

    p = tmp_path / "plan.json"
    p.write_text(json.dumps({
        "kind": plan_mod.PLAN_KIND,
        "schema_version": plan_mod.PLAN_SCHEMA_VERSION,
        "faults": [{"at": "1 s", "op": "kill_chip", "chip": 6}],
    }))
    assert main([str(p)]) == 0
    assert main(["--mesh-size", "8", str(p)]) == 0
    assert main(["--mesh-size", "4", str(p)]) == 2
    err = capsys.readouterr().err
    assert "out of range" in err and "INVALID" in err
    assert main(["--mesh-size", "nope", str(p)]) == 2


def test_serve_submit_rejects_out_of_mesh_kill_chip(tmp_path):
    """Daemon-level chaos plans bounds-check kill_chip against the
    sweep's own mesh size, and a malformed plan is a clean ServeError
    (HTTP 400) — not a dead handler thread (the pre-elastic escape)."""
    from shadow_tpu.serve.daemon import ServeError, ServeOptions, \
        ShadowDaemon

    daemon = ShadowDaemon(ServeOptions(
        state_dir=str(tmp_path), cache_dir=str(tmp_path / "cache"),
    ))
    doc = {
        **_fleet_job_cfg(1, 1),
        "sweep": {"name": "v", "lanes": 1,
                  "matrix": {"general.seed": [1, 2]}},
    }
    with pytest.raises(ServeError, match="out of range"):
        daemon.submit(doc, backend_faults=[
            {"at": "0.5 s", "op": "kill_chip", "chip": 7}
        ])
    # in-bounds passes admission validation and queues
    out = daemon.submit(doc, backend_faults=[
        {"at": "0.5 s", "op": "kill_chip", "chip": 1}
    ])
    assert "id" in out


def test_metrics_v12_elastic_and_absent_on_non_mesh(baseline, tmp_path):
    """Schema v12: the elastic run's metrics carry the mesh.* relayout
    counters + chips_up/chips_total gauges and strict-validate; a
    non-mesh run's document carries NO mesh keys."""
    from shadow_tpu.obs import metrics as obs_metrics

    r = _runner(_BASE, tmp_path, faults=[
        {"at": "1 s", "op": "kill_chip", "chip": 2, "recover_after": 2}
    ])
    sim = r.run()
    reg = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_device(sim, reg)
    doc = reg.to_doc()
    assert_current_metrics_schema(doc)
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    assert doc["counters"]["mesh.relayouts"] == 1
    assert doc["counters"]["mesh.re_expansions"] == 1
    assert doc["counters"]["mesh.chips_lost"] == 1
    assert doc["counters"]["mesh.relayout_downtime_ns"] > 0
    assert doc["counters"]["resilience.chip_losses"] == 1
    assert doc["gauges"]["mesh.chips_up"] == 4
    assert doc["gauges"]["mesh.chips_total"] == 4
    bad = dict(doc)
    bad["counters"] = {**doc["counters"], "mesh.relayouts": -1}
    with pytest.raises(ValueError, match="mesh"):
        obs_metrics.validate_metrics_doc(bad)

    plain = build_simulation(_cfg(shards=1, stop=2))
    plain.run()
    reg2 = obs_metrics.MetricsRegistry()
    obs_metrics.snapshot_device(plain, reg2)
    doc2 = reg2.to_doc()
    assert not any(k.startswith("mesh.") for k in doc2["counters"])
    assert not any(k.startswith("mesh.") for k in doc2["gauges"])


def test_mesh_posture_for_healthz():
    """FleetSimulation.mesh_posture: chips up/total for /healthz; {} on
    a non-islands fleet (no mesh keys on non-mesh runs)."""
    from shadow_tpu.fleet import JobSpec, build_fleet

    fleet = build_fleet(
        [JobSpec(name="a", config=_fleet_job_cfg(1, 2))], lanes=1,
    )
    p = fleet.mesh_posture()
    assert p["chips_up"] == 2 and p["chips_total"] == 2
    assert p["shard_map"] == 0 and p["chips_down"] == []

    flat = build_fleet(
        [JobSpec(name="b", config=_cfg(n=4, shards=1, stop=2))], lanes=1,
    )
    assert flat.mesh_posture() == {}
