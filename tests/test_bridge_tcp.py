"""TCP over the CPU↔TPU seam (procs/bridge.py + net/tcp.py): REAL processes
carry TCP connections through the device TCP state machine — handshake,
Reno congestion control, retransmission and delivery timing all computed by
the window kernel; payload bytes stay host-side and are matched to
device-reported in-order advances.
"""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = [
    pytest.mark.skipif(
        not build_mod.toolchain_available(), reason="no native toolchain"
    ),
    # compiling the device TCP machine for six configs takes several
    # hundred seconds even with a warm XLA cache — out of the tier-1
    # budgeted run, invoke this file directly instead
    pytest.mark.slow,
]

NS_PER_MS = 1_000_000


def _yaml(apps, lat_ms, loss=0.0, nbytes=65536, stop="60 s", seed=7):
    return f"""
general:
  stop_time: {stop}
  seed: {seed}
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "{lat_ms} ms" packet_loss {loss} ]
      ]
experimental:
  use_device_network: true
  use_device_tcp: true
  event_capacity: 4096
  events_per_host_per_window: 8
hosts:
  server:
    processes:
      - path: {apps['tcp_sink']}
        args: "9001"
  client:
    processes:
      - path: {apps['tcp_source']}
        args: server 9001 {nbytes}
        start_time: 1 s
"""


def test_tcp_bulk_through_device_network(apps):
    """A real tcp_source/tcp_sink pair moves a bulk stream through the
    device TCP machine; every byte arrives, and the device carried the
    segments (handshake + data are visible in device counters)."""
    d = build_process_driver(_yaml(apps, lat_ms=20, nbytes=65536))
    assert d.bridge is not None and d.bridge.with_tcp
    d.run()
    client, server = d.procs  # hosts are name-sorted: client before server
    assert client.exit_code == 0, client.stderr
    assert server.exit_code == 0, server.stderr
    assert b"sent 65536 bytes" in client.stdout
    assert b"received 65536 bytes" in server.stdout
    c = d.bridge.sim.counters()
    # >= 45 MSS-sized data segments plus handshake/teardown control
    assert c["packets_delivered"] > 45
    trk = d.host_trackers()
    assert trk["server"]["rx_bytes"] == 65536


def test_tcp_bridge_deterministic(apps):
    """Byte-identical reruns with the device TCP machine in the loop."""
    def run_once():
        d = build_process_driver(_yaml(apps, lat_ms=10, nbytes=20000))
        d.run()
        return [p.stdout for p in d.procs]

    assert run_once() == run_once()


def test_tcp_bridge_lossy_stream_is_reliable(apps):
    """With a lossy edge, device Reno retransmissions still deliver every
    byte in order — loss shows up in device counters, not in the stream."""
    # seed 42: seed 7's host-0 draw stream happens to contain no value
    # above 0.85 in its first ~46 draws (a 1-in-1000 outlier), so it would
    # see no drops at 15% loss
    d = build_process_driver(
        _yaml(apps, lat_ms=5, loss=0.15, nbytes=60000, stop="120 s", seed=42)
    )
    d.run()
    client, server = d.procs
    assert client.exit_code == 0, client.stderr
    assert server.exit_code == 0, server.stderr
    assert b"received 60000 bytes" in server.stdout
    c = d.bridge.sim.counters()
    assert c["packets_dropped_loss"] > 0
    tcp = d.bridge.sim.state.subs["tcp"]
    assert int(tcp.retransmits) > 0


def test_tcp_bridge_connect_refused(apps):
    """A connect to a port with no listener gets an on-device RST and the
    managed process sees ECONNREFUSED (not a forever-parked connect)."""
    yaml = f"""
general:
  stop_time: 30 s
  seed: 5
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" ]
      ]
experimental:
  use_device_network: true
  use_device_tcp: true
  event_capacity: 1024
  events_per_host_per_window: 8
hosts:
  server:
    processes:
      - path: {apps['tcp_sink']}
        args: "8000"
  client:
    processes:
      - path: {apps['tcp_refused']}
        args: server 9999
        start_time: 1 s
"""
    d = build_process_driver(yaml)
    d.run()
    client = next(p for p in d.procs if "tcp_refused" in p.args[0])
    assert client.exit_code == 0, client.stderr
    assert b"refused" in client.stdout
    # the mirror slot was recycled after the RST teardown
    free = d.bridge._tcp_free[client.host.index]
    assert len(free) == d.bridge.child_base


def test_tcp_bridge_serial_connections_recycle_slots(apps):
    """More sequential connections than CPU-owned slots (child_base=4 at
    sockets_per_host=8): TIME_WAIT recycling must return slots early or the
    5th connect would fail with ENOBUFS."""
    yaml = f"""
general:
  stop_time: 120 s
  seed: 11
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "10 ms" ]
      ]
experimental:
  use_device_network: true
  use_device_tcp: true
  event_capacity: 4096
  events_per_host_per_window: 8
hosts:
  server:
    processes:
      - path: {apps['tcp_multi_sink']}
        args: 9001 6
  client:
    processes:
      - path: {apps['tcp_serial']}
        args: server 9001 6 4000
        start_time: 1 s
"""
    d = build_process_driver(yaml)
    d.run()
    client, server = d.procs
    assert client.exit_code == 0, (client.stdout, client.stderr)
    assert b"all 6 connections done" in client.stdout
    assert b"total 24000 bytes over 6 connections" in server.stdout


def test_tcp_send_backpressure_bounded_buffer(apps):
    """ADVICE r1: device-carried sends must not buffer the whole stream
    host-side. With a small socket_send_buffer the blocking writer parks at
    the cap and drains as the device reports in-order advances: the
    host-side tx_queue never exceeds the cap, and the transfer still
    completes (reference analog: tcp.c bounded send buffer blocking the
    writer)."""
    yaml = _yaml(apps, lat_ms=20, nbytes=200000).replace(
        "use_device_tcp: true",
        "use_device_tcp: true\n  socket_send_buffer: 8192",
    )
    d = build_process_driver(yaml)
    assert d.socket_send_buffer == 8192
    peak = 0

    def hb(drv):
        nonlocal peak
        for end in drv._dev_tcp.values():
            peak = max(peak, len(end.tx_queue))

    d.heartbeat_interval = 20 * NS_PER_MS
    d.heartbeat_fn = hb
    d.run()
    client, server = d.procs
    assert client.exit_code == 0, client.stderr
    assert server.exit_code == 0, server.stderr
    assert b"sent 200000 bytes" in client.stdout
    assert b"received 200000 bytes" in server.stdout
    assert 0 < peak <= 8192, f"host-side buffering exceeded sndbuf: {peak}"
