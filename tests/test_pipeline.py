"""Pipelined CPU↔TPU handoff (ISSUE 15): chain-equality matrix
pipelined-vs-serial × {conservative, optimistic} × {global, islands,
fleet}, forced-drain barrier points (fault marks, gear shifts,
checkpoint boundaries, pressure rungs mid-flight), the supervisor
issue/await split, and the pipeline.* telemetry plane.

The load-bearing property: the two-slot pipeline changes WHEN dispatches
are enqueued — never what they compute. Every adopted speculative
dispatch is a pure function of exactly the inputs the serial loop would
have passed (core/pipeline.py recompute rule), so every cell of the
matrix must reproduce the serial driver's audit digest chain
bit-for-bit, including runs whose handoffs mutate state (injections,
gear shifts, checkpoint ring writes, pressure ladders).
"""

import json

import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.core import pipeline as pipeline_mod
from shadow_tpu.core import simtime
from shadow_tpu.faults import plan as plan_mod
from shadow_tpu.core.supervisor import (
    BackendLost,
    BackendSupervisor,
    PendingDispatch,
)
from shadow_tpu.fleet import JobSpec, build_fleet
from shadow_tpu.obs import metrics as obs_metrics
from shadow_tpu.sim import build_simulation

NEVER = int(simtime.NEVER)

GML = """\
graph [
  node [ id 0 ]
  node [ id 1 ]
  node [ id 2 ]
  node [ id 3 ]
  edge [ source 0 target 1 latency "40 ms" ]
  edge [ source 1 target 2 latency "55 ms" ]
  edge [ source 2 target 3 latency "70 ms" ]
  edge [ source 3 target 0 latency "85 ms" ]
  edge [ source 0 target 2 latency "60 ms" ]
  edge [ source 1 target 3 latency "75 ms" ]
]
"""


def _cfg(pipelined=True, stop=6, seed=11, hosts_per=2, runtime=None,
         **exp):
    hosts = {}
    for v in range(4):
        hosts[f"h{v}"] = {
            "quantity": hosts_per, "network_node_id": v,
            "app_model": "phold",
            "app_options": {
                "msgload": 1,
                "runtime": (stop - 1) if runtime is None else runtime,
            },
        }
    experimental = {
        "event_capacity": 1024, "events_per_host_per_window": 8,
        "outbox_slots": 8, "inbox_slots": 4,
        "pipelined_dispatch": pipelined,
    }
    experimental.update(exp)
    return {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "gml", "inline": GML}},
        "experimental": experimental,
        "hosts": hosts,
    }


def _build(pipelined=True, faults=None, **kw):
    sim = build_simulation(_cfg(pipelined=pipelined, **kw))
    if faults is not None:
        sim.attach_faults(plan_mod.parse_fault_plan(faults))
    return sim


def _chain(sim):
    return sim.audit_chain(), sim.counters()["events_committed"]


@pytest.fixture(scope="module")
def serial_ref():
    """The serial global conservative chain every pipelined cell must
    reproduce bit-for-bit."""
    sim = build_simulation(_cfg(pipelined=False))
    assert sim.pipelined_dispatch is False
    sim.run(windows_per_dispatch=16)
    assert sim.pipeline_stats() == {}  # serial arm: no pipeline plane
    return _chain(sim)


# ---------------------------------------------------------------------------
# chain-equality matrix: pipelined vs serial × {cons, opt} × layouts
# ---------------------------------------------------------------------------


def test_global_conservative_pipelined_matches(serial_ref):
    sim = build_simulation(_cfg())
    assert sim.pipelined_dispatch is True  # on by default
    sim.run(windows_per_dispatch=16)
    assert _chain(sim) == serial_ref
    st = sim.pipeline_stats()
    # a clean fused run issues ahead at (nearly) every boundary and
    # never has to discard or force-drain
    assert st["issued_ahead"] > 0
    assert st["recompute_discards"] == 0
    assert st["forced_drains"] == 0
    assert st["overlap_ns"] > 0


def test_global_stepwise_pipelined_matches(serial_ref):
    sim = build_simulation(_cfg())
    sim.run_stepwise()
    assert _chain(sim) == serial_ref
    assert sim.pipeline_stats()["issued_ahead"] > 0


def test_global_optimistic_pipelined_matches(serial_ref):
    serial = build_simulation(_cfg(pipelined=False))
    serial.run_optimistic()
    assert _chain(serial) == serial_ref
    sim = build_simulation(_cfg())
    sim.run_optimistic()
    assert _chain(sim) == serial_ref
    assert sim.pipeline_stats()["issued_ahead"] > 0


def test_islands_async_pipelined_matches(serial_ref):
    exp = {"num_shards": 2, "exchange_slots": 16}
    serial = build_simulation(_cfg(pipelined=False, **exp))
    serial.run(windows_per_dispatch=16)
    assert _chain(serial) == serial_ref
    sim = build_simulation(_cfg(**exp))
    assert sim._async is True  # the fused async driver is the default
    sim.run(windows_per_dispatch=16)
    assert _chain(sim) == serial_ref
    assert sim.pipeline_stats()["issued_ahead"] > 0


def test_islands_optimistic_pipelined_matches(serial_ref):
    # the islands optimistic driver is host-stepped (not issued ahead)
    # but must stay chain-exact with the knob on
    sim = build_simulation(_cfg(num_shards=2, exchange_slots=16))
    sim.run_optimistic()
    assert _chain(sim) == serial_ref


def _fleet_jobs(pipelined, n=3):
    # runtime is kernel-shaping (handler constant) and must match across
    # jobs; stop_time and seed are data-plane sweep axes
    return [
        JobSpec(f"job{i}", _cfg(pipelined=pipelined, seed=11 + i,
                                stop=4 + i, runtime=3))
        for i in range(n)
    ]


def test_fleet_pipelined_matches_serial_and_solo():
    serial = build_fleet(_fleet_jobs(False), lanes=2)
    assert serial.pipelined_dispatch is False
    serial.run()
    piped = build_fleet(_fleet_jobs(True), lanes=2)
    assert piped.pipelined_dispatch is True  # adopted from template job
    piped.run()
    rows_s = {r["name"]: r for r in serial.results()}
    rows_p = {r["name"]: r for r in piped.results()}
    assert rows_s.keys() == rows_p.keys()
    for name, rs in rows_s.items():
        rp = rows_p[name]
        assert rp["events_committed"] == rs["events_committed"], name
        assert rp["audit"]["chain"] == rs["audit"]["chain"], name
    # solo parity for one job closes the loop to the global engine
    solo = build_simulation(_cfg(seed=12, stop=5, runtime=3))
    solo.run(windows_per_dispatch=16)
    assert rows_p["job1"]["audit"]["chain"] == solo.audit_chain()
    assert piped.pipeline_stats()["issued_ahead"] > 0
    assert serial.pipeline_stats() == {}


# ---------------------------------------------------------------------------
# forced-drain barrier points: state-mutating handoffs stay serial and
# chain-exact
# ---------------------------------------------------------------------------


def test_fault_mark_forces_drain_chain_exact(serial_ref):
    faults = [{"op": "kill_host", "at": "2 s", "host": 5}]
    serial = _build(pipelined=False, faults=faults)
    serial.run(windows_per_dispatch=4)
    piped = _build(faults=faults)
    piped.run(windows_per_dispatch=4)
    assert _chain(piped) == _chain(serial)
    # the injection fired at the same frontier in both arms
    assert piped.fault_counters["hosts_quarantined"] == 1
    assert (piped.fault_counters["events_drained"]
            == serial.fault_counters["events_drained"])
    st = piped.pipeline_stats()
    # every boundary from the quarantine on is a barrier point (the
    # recurring dead-host drain mutates state), so the pipeline must
    # have refused to speculate at least once
    assert st["forced_drains"] > 0


def test_gear_shift_invalidates_speculation_chain_exact():
    exp = {"pool_gears": 3, "event_capacity": 2048}
    serial = build_simulation(_cfg(pipelined=False, **exp))
    serial.run(windows_per_dispatch=4)
    piped = build_simulation(_cfg(**exp))
    piped.run(windows_per_dispatch=4)
    assert _chain(piped) == _chain(serial)


def test_checkpoint_boundary_forces_drain(tmp_path, serial_ref):
    def run(pipelined, sub):
        d = tmp_path / sub
        d.mkdir()
        sim = build_simulation(_cfg(pipelined=pipelined))
        sim.configure_auto_checkpoint(str(d), int(2e9), retain=4)
        sim.run(windows_per_dispatch=16)
        return sim, sorted(p.name for p in d.glob("ckpt-*.npz"))

    serial, rings_s = run(False, "serial")
    piped, rings_p = run(True, "piped")
    assert _chain(piped) == _chain(serial) == serial_ref
    assert rings_p == rings_s and rings_p  # same ring cadence
    assert piped.pipeline_stats()["forced_drains"] > 0


def test_pressure_rung_mid_flight_chain_exact(serial_ref):
    faults = [{"op": "exhaust_backend", "at": "2 s", "recover_after": 1}]
    exp = {"pool_gears": 2, "event_capacity": 2048}
    serial = _build(pipelined=False, faults=faults, **exp)
    serial.run(windows_per_dispatch=8)
    piped = _build(faults=faults, **exp)
    piped.run(windows_per_dispatch=8)
    assert _chain(piped) == _chain(serial)
    assert piped.resilience_stats()["exhaustions"] > 0


def test_kill_backend_on_pipelined_run_drains_and_resumes(tmp_path):
    faults = [{"op": "kill_backend", "at": "2 s", "recover_after": 1}]
    ref = build_simulation(_cfg())
    ref.run(windows_per_dispatch=16)
    sim = _build(faults=faults)
    sim.checkpoint_dir = str(tmp_path)
    sim.attach_supervisor(
        BackendSupervisor(policy="wait", sleep=lambda s: None)
    )
    sim.run(windows_per_dispatch=16)
    assert _chain(sim) == _chain(ref)
    rs = sim.resilience_stats()
    assert rs["backend_losses"] >= 1 and rs["hot_resumes"] >= 1


# ---------------------------------------------------------------------------
# supervisor issue/await split units
# ---------------------------------------------------------------------------


class _FakeSim:
    def __init__(self):
        self.drains = []

    def _drain_to_checkpoint(self, reason, ckpt_dir=None):
        self.drains.append(reason)
        return None

    def _rebind_kernels(self):
        pass


def _fake_sup(**kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("probe_fn", lambda: True)
    sup = BackendSupervisor(**kw)
    sup.bind(_FakeSim())
    return sup


def test_transient_failure_on_issued_ahead_walks_retry_ladder():
    sup = _fake_sup(max_retries=3)
    calls = {"issue": 0, "fetch": 0}

    def issue():
        calls["issue"] += 1
        return "futures"

    def fetch(out):
        assert out == "futures"
        calls["fetch"] += 1
        if calls["fetch"] < 3:
            raise RuntimeError("ABORTED: collective interrupted, retry")
        return "result"

    p = sup.issue("run_to", issue, fetch)
    assert calls["issue"] == 1  # issued ahead, exactly once
    out = sup.await_result(p)
    assert out == "result"
    # retries re-ran BOTH halves (issue re-reads bound kernels)
    assert calls["issue"] == 3
    assert sup.counters["retries"] == 2
    assert sup.counters["dispatches"] == 3


def test_backend_loss_on_issued_ahead_drains_cleanly():
    sup = _fake_sup(policy="abort")

    def fetch(out):
        raise RuntimeError("backend_unavailable: socket closed")

    p = sup.issue("run_to", lambda: "futures", fetch)
    with pytest.raises(BackendLost):
        sup.await_result(p)
    assert sup._sim.drains == ["backend_lost:run_to"]
    assert sup.counters["backend_losses"] == 1


def test_issue_skipped_while_disrupted_then_awaits_clean():
    sup = _fake_sup(policy="wait")
    sup.inject_kill(recover_after=0)
    assert sup.pending_disruption
    calls = {"issue": 0}

    def issue():
        calls["issue"] += 1
        return "f"

    p = sup.issue("run_to", issue, lambda out: out)
    assert calls["issue"] == 0  # launch skipped against the dead backend
    out = sup.await_result(p)  # recovery (hot resume), then fresh issue
    assert out == "f" and calls["issue"] == 1
    assert sup.counters["hot_resumes"] == 1


def test_injected_exhaust_fires_on_awaited_half():
    sup = _fake_sup()
    rungs = []
    sup._sim._pressure_ladder_step = lambda label: (
        rungs.append(label) or True
    )
    sup.inject_exhaust(recover_after=1)
    p = sup.issue("run_to", lambda: "f", lambda out: out)
    assert sup.await_result(p) == "f"
    assert len(rungs) == 1  # one ladder rung per injected failure
    assert sup.counters["exhaustions"] == 1


def test_pending_dispatch_direct_and_abandon():
    p = PendingDispatch.direct("x", lambda: 41, lambda out: out + 1)
    assert p.await_direct() == 42
    # claim is once-only: a second await re-runs the halves
    assert p.await_direct() == 42
    calls = []
    p2 = PendingDispatch.direct("y", lambda: calls.append(1) or 1,
                                lambda out: out)
    p2.abandon()
    assert p2.claim() is None  # abandoned futures are never observed


def test_two_slot_pipeline_recompute_rule():
    stats = pipeline_mod.new_stats()
    pipe = pipeline_mod.TwoSlotPipeline(stats)
    tok = object()
    p = PendingDispatch.direct("z", lambda: 7, lambda out: out)
    pipe.put(p, tok, ("args",))
    # args drift → discard + recompute tally
    assert pipe.take(tok, ("other",)) is None
    assert stats["recompute_discards"] == 1
    p2 = PendingDispatch.direct("z", lambda: 7, lambda out: out)
    pipe.put(p2, tok, ("args",))
    # state drift → invalidate discards
    pipe.invalidate(object())
    assert not pipe.pending and stats["recompute_discards"] == 2
    p3 = PendingDispatch.direct("z", lambda: 7, lambda out: out)
    pipe.put(p3, tok, ("args",))
    assert pipe.take(tok, ("args",)) is p3  # exact match adopts
    assert stats["issued_ahead"] == 3
    assert stats["overlap_ns"] >= 0


# ---------------------------------------------------------------------------
# telemetry: pipeline.* metrics (schema v14) + issue/await/host_drain spans
# ---------------------------------------------------------------------------


def test_pipeline_metrics_schema_v14(tmp_path):
    sim = build_simulation(_cfg())
    sim.run(windows_per_dispatch=16)
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    path = tmp_path / "m.json"
    doc = session.metrics.dump(str(path))
    assert_current_metrics_schema(doc)
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    c = doc["counters"]
    assert c["pipeline.issued_ahead"] > 0
    assert c["pipeline.overlap_ns"] > 0
    assert c["pipeline.forced_drains"] == 0
    assert c["pipeline.recompute_discards"] == 0


def test_serial_run_emits_no_pipeline_keys(tmp_path):
    sim = build_simulation(_cfg(pipelined=False))
    sim.run(windows_per_dispatch=16)
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(str(tmp_path / "m.json"))
    assert not [k for k in doc["counters"] if k.startswith("pipeline.")]
    assert not [k for k in doc["gauges"] if k.startswith("pipeline.")]


def test_trace_spans_and_overlap_efficiency(tmp_path):
    import sys

    sys.path.insert(0, "tools")
    try:
        import trace_summary
    finally:
        sys.path.pop(0)
    from shadow_tpu.obs.trace import ChromeTracer

    def run(pipelined, name):
        sim = build_simulation(_cfg(pipelined=pipelined))
        tracer = ChromeTracer()
        sim.obs_session = obs_metrics.ObsSession(tracer=tracer)
        sim.run(windows_per_dispatch=16)
        path = tmp_path / name
        tracer.write(str(path))
        with open(path) as f:
            return json.load(f)

    doc = run(True, "piped.json")
    names = {e.get("name") for e in doc["traceEvents"]
             if e.get("ph") == "X"}
    assert {"issue", "await", "host_drain"} <= names
    ov = trace_summary.overlap_stats(doc)
    assert ov is not None and ov["issued_ahead"] > 0
    assert ov["adopted"] > 0
    assert 0.0 <= ov["overlap_efficiency"] <= 1.0
    # the aggregate summary still reads the span rows
    rows, _ = trace_summary.summarize(doc)
    assert any(r["name"] == "issue" for r in rows)

    serial = run(False, "serial.json")
    snames = {e.get("name") for e in serial["traceEvents"]
              if e.get("ph") == "X"}
    assert "issue" not in snames and "await" not in snames
    assert trace_summary.overlap_stats(serial) is None


def test_handoff_hook_runs_and_mutation_discards_speculation():
    seen = []

    sim = build_simulation(_cfg())
    sim.add_handoff_hook(lambda s, mn: seen.append(mn))
    sim.run(windows_per_dispatch=16)
    assert seen and all(isinstance(x, int) for x in seen)
    ref = build_simulation(_cfg(pipelined=False))
    ref.run(windows_per_dispatch=16)
    assert _chain(sim) == _chain(ref)

    # a state-mutating hook triggers the recompute rule, chains intact
    def mutate(s, mn):
        s.state = s.state.replace(now=s.state.now + 0)

    sim2 = build_simulation(_cfg())
    sim2.add_handoff_hook(mutate)
    sim2.run(windows_per_dispatch=16)
    assert _chain(sim2) == _chain(ref)
    st = sim2.pipeline_stats()
    assert st["recompute_discards"] > 0
