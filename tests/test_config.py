import pytest

from shadow_tpu.core import simtime
from shadow_tpu.core.config import ConfigError, load_config

pytestmark = pytest.mark.quick


PHOLD_LIKE = """
general:
  stop_time: 10
network:
  graph:
    type: gml
    inline: |
      graph [
        directed 0
        node [
          id 0
          country_code "US"
          bandwidth_down "81920 Kibit"
          bandwidth_up "81920 Kibit"
        ]
        edge [
          source 0
          target 0
          latency "50 ms"
          packet_loss 0.0
        ]
      ]
hosts:
  peer:
    quantity: 3
    processes:
    - path: test-phold
      args: loglevel=info quantity=3
      start_time: 1
"""


def test_load_phold_like():
    cfg = load_config(PHOLD_LIKE)
    assert cfg.general.stop_time == 10 * simtime.NS_PER_SEC
    assert cfg.general.seed == 1
    # reference names every host name1..nameN when quantity > 1
    assert [h.name for h in cfg.hosts] == ["peer1", "peer2", "peer3"]
    assert cfg.hosts[0].processes[0].path == "test-phold"
    assert cfg.hosts[0].processes[0].start_time == simtime.NS_PER_SEC
    assert "graph [" in cfg.graph_gml()


def test_host_defaults_merge():
    cfg = load_config(
        {
            "general": {"stop_time": "1 s", "seed": 7},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "host_defaults": {"bandwidth_down": "10 Mbit", "country_code_hint": "US"},
            "hosts": {
                "a": {},
                "b": {"bandwidth_down": "20 Mbit"},
            },
        }
    )
    a = next(h for h in cfg.hosts if h.name == "a")
    b = next(h for h in cfg.hosts if h.name == "b")
    assert a.bandwidth_down == 10**7
    assert b.bandwidth_down == 2 * 10**7
    assert a.country_code_hint == "US"
    assert b.country_code_hint == "US"


def test_unknown_field_rejected():
    with pytest.raises(ConfigError):
        load_config(
            {
                "general": {"stop_time": 1, "bogus": True},
                "network": {"graph": {"type": "1_gbit_switch"}},
            }
        )


def test_required_sections():
    with pytest.raises(ConfigError):
        load_config({"network": {"graph": {"type": "1_gbit_switch"}}})
    with pytest.raises(ConfigError):
        load_config({"general": {"stop_time": 1}})


def test_deterministic_host_order():
    cfg = load_config(
        {
            "general": {"stop_time": 1},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {"zeta": {}, "alpha": {}, "mid": {}},
        }
    )
    assert [h.name for h in cfg.hosts] == ["alpha", "mid", "zeta"]
