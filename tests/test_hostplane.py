"""PARSIR-style multi-worker host plane (ISSUE 17): chain-equality
matrix host_workers {2,4} × {conservative, optimistic} × {global,
islands, fleet} in pipelined AND serial arms, migration re-pin
determinism, kill-mid-drain resume parity, worker-exception serial
fallback, the canonical (vt, gid, seq) runnable-queue key, and the
hostplane.* telemetry plane (schema v15).

The load-bearing property: the host plane changes WHICH THREAD executes
partition-local handoff work — never what it computes or the order its
effects commit. Worker results merge on the coordinator in the exact
canonical order the serial drain uses (core/hostplane.py), so every
multi-worker cell must reproduce the `host_workers: 1` audit digest
chain bit-for-bit, including runs that migrate hosts mid-flight, resume
from a checkpoint ring, or lose a worker to an exception.
"""

import heapq

import numpy as np
import pytest

from _contracts import assert_current_metrics_schema

from shadow_tpu.core import hostplane as hostplane_mod
from shadow_tpu.core import simtime
from shadow_tpu.fleet import JobSpec, build_fleet
from shadow_tpu.obs import metrics as obs_metrics
from shadow_tpu.sim import build_simulation

GML = """\
graph [
  node [ id 0 ]
  node [ id 1 ]
  node [ id 2 ]
  node [ id 3 ]
  edge [ source 0 target 1 latency "40 ms" ]
  edge [ source 1 target 2 latency "55 ms" ]
  edge [ source 2 target 3 latency "70 ms" ]
  edge [ source 3 target 0 latency "85 ms" ]
  edge [ source 0 target 2 latency "60 ms" ]
  edge [ source 1 target 3 latency "75 ms" ]
]
"""


def _cfg(workers=1, stop=6, seed=11, runtime=None, **exp):
    hosts = {}
    for v in range(4):
        hosts[f"h{v}"] = {
            "quantity": 2, "network_node_id": v,
            "app_model": "phold",
            "app_options": {
                "msgload": 1,
                "runtime": (stop - 1) if runtime is None else runtime,
            },
        }
    experimental = {
        "event_capacity": 1024, "events_per_host_per_window": 8,
        "outbox_slots": 8, "inbox_slots": 4,
        "host_workers": workers,
    }
    experimental.update(exp)
    return {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "gml", "inline": GML}},
        "experimental": experimental,
        "hosts": hosts,
    }


def _chain(sim):
    return sim.audit_chain(), sim.counters()["events_committed"]


def _recorded_run(sim, runner=None):
    """Run with a sharded recorder hook attached; return (chain, the
    sorted (frontier, gid) coverage the fan-out visited)."""
    hits = []
    sim.add_handoff_hook(
        lambda s, mn, gid: hits.append((int(mn), int(gid))), sharded=True
    )
    (runner or (lambda s: s.run(windows_per_dispatch=16)))(sim)
    return _chain(sim), sorted(hits)


@pytest.fixture(scope="module")
def serial_ref():
    """The host_workers=1 conservative chain every multi-worker cell of
    every driver family must reproduce bit-for-bit. Hook COVERAGE is
    compared within a driver family (each family drains at its own
    frontiers), so cells build their own same-driver serial arm."""
    sim = build_simulation(_cfg(workers=1))
    chain, hits = _recorded_run(sim)
    assert sim.hostplane_stats() == {}  # serial arm: no plane, no keys
    assert hits  # the inline serial fan-out still visits every partition
    return chain, hits


# ---------------------------------------------------------------------------
# chain-equality matrix: workers × driver × layout, pipelined + serial
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("workers", [2, 4])
@pytest.mark.parametrize("pipelined", [True, False])
def test_global_conservative_matrix(serial_ref, workers, pipelined):
    sim = build_simulation(
        _cfg(workers=workers, pipelined_dispatch=pipelined)
    )
    chain, hits = _recorded_run(sim)
    assert (chain, hits) == serial_ref
    st = sim.hostplane_stats()
    assert st["workers"] == workers
    assert st["sharded_drains"] > 0
    assert st["serial_fallbacks"] == 0


@pytest.mark.parametrize("workers", [2, 4])
def test_global_optimistic_matrix(serial_ref, workers):
    serial = build_simulation(_cfg(workers=1))
    ref = _recorded_run(serial, lambda s: s.run_optimistic())
    assert ref[0] == serial_ref[0]  # optimistic matches conservative
    sim = build_simulation(_cfg(workers=workers))
    assert _recorded_run(sim, lambda s: s.run_optimistic()) == ref
    assert sim.hostplane_stats()["sharded_drains"] > 0


@pytest.mark.parametrize("workers", [2, 4])
def test_islands_async_matrix(serial_ref, workers):
    exp = {"num_shards": 2, "exchange_slots": 16}
    serial = build_simulation(_cfg(workers=1, **exp))
    ref = _recorded_run(serial)
    assert ref[0] == serial_ref[0]  # islands matches the global engine
    sim = build_simulation(_cfg(workers=workers, **exp))
    assert sim._async is True  # the fused async driver is the default
    assert _recorded_run(sim) == ref
    assert sim.hostplane_stats()["sharded_drains"] > 0


def test_islands_optimistic_matches(serial_ref):
    sim = build_simulation(
        _cfg(workers=4, num_shards=2, exchange_slots=16)
    )
    chain, hits = _recorded_run(sim, lambda s: s.run_optimistic())
    assert chain == serial_ref[0]
    assert hits and sim.hostplane_stats()["sharded_drains"] > 0


def _fleet_jobs(workers, n=3):
    # runtime is kernel-shaping and must match across jobs; stop_time
    # and seed are data-plane sweep axes
    return [
        JobSpec(f"job{i}", _cfg(workers=workers, seed=11 + i,
                                stop=4 + i, runtime=3))
        for i in range(n)
    ]


@pytest.mark.parametrize("workers", [2, 4])
def test_fleet_matrix(workers):
    def run(w):
        fleet = build_fleet(_fleet_jobs(w), lanes=2)
        hits = []
        fleet.add_handoff_hook(
            lambda f, mn, lane: hits.append(int(lane)), sharded=True
        )
        fleet.run()
        return {r["name"]: (r["audit"]["chain"], r["events_committed"])
                for r in fleet.results()}, sorted(hits), fleet

    rows_s, hits_s, serial = run(1)
    rows_m, hits_m, multi = run(workers)
    assert rows_m == rows_s and rows_m
    assert hits_m == hits_s and hits_m  # same per-lane fan-out coverage
    assert serial.hostplane_stats() == {}
    st = multi.hostplane_stats()
    assert st["workers"] == workers and st["sharded_drains"] > 0


# ---------------------------------------------------------------------------
# migration re-pin determinism
# ---------------------------------------------------------------------------


def test_migration_repins_and_stays_chain_exact():
    """A live migration mid-run permutes slot_of; the plane re-pins from
    the new table on the next drain and the chain still matches the
    serial migrated run bit-for-bit."""
    exp = {"num_shards": 2, "exchange_slots": 16, "rebalance": True}

    def run(workers):
        sim = build_simulation(_cfg(workers=workers, **exp))
        hits = []
        sim.add_handoff_hook(
            lambda s, mn, gid: hits.append((int(mn), int(gid))),
            sharded=True,
        )
        sim.run(until=3 * simtime.NS_PER_SEC, windows_per_dispatch=16)
        sim.rebalance_now()
        assert sim.rebalances == 1
        sim.run(windows_per_dispatch=16)
        return sim, _chain(sim), sorted(hits)

    serial, chain_s, hits_s = run(1)
    multi, chain_m, hits_m = run(4)
    assert chain_m == chain_s
    assert hits_m == hits_s
    # the slot cache tracked the layout epoch: post-migration drains
    # derived pins from the CURRENT slot_of table
    cached = multi._hostplane_slot_cache
    assert cached is not None and cached[0] == 1
    assert np.array_equal(
        cached[1], np.asarray(multi.params.slot_of).reshape(-1)
    )


def test_repin_determinism_unit():
    """Same slot-table history -> same pins, same move count, on every
    run (the placement seam is the only pin input)."""
    def history(plane, st):
        pins = []
        for sm in (None, [3, 2, 1, 0], [3, 2, 1, 0], [0, 1, 2, 3]):
            plane.set_slot_map(sm)
            plane.drain([
                hostplane_mod.HostAction(0, g, lambda: None)
                for g in range(4)
            ])
            with plane._lock:
                pins.append(dict(plane._pins))
        plane.close()
        return pins, st["pin_moves"]

    a = history(*(lambda s: (hostplane_mod.HostPlane(2, s), s))(
        hostplane_mod.new_stats(2)))
    b = history(*(lambda s: (hostplane_mod.HostPlane(2, s), s))(
        hostplane_mod.new_stats(2)))
    assert a == b
    pins, moves = a
    assert pins[0] == {0: 0, 1: 1, 2: 0, 3: 1}   # identity: gid % 2
    assert pins[1] == {0: 1, 1: 0, 2: 1, 3: 0}   # reversed table
    assert pins[2] == pins[1]                     # stable under no change
    assert pins[3] == pins[0]                     # migrated back
    assert moves == 8                             # two full re-pins of 4


# ---------------------------------------------------------------------------
# kill mid-drain -> resume parity
# ---------------------------------------------------------------------------


def test_kill_mid_run_resume_matches_serial(tmp_path):
    """Auto-checkpoint a 4-worker run, kill it between handoffs (abandon
    the process state), resume in a fresh 4-worker build: the final
    chain equals the uninterrupted serial run's."""
    serial = build_simulation(_cfg(workers=1))
    serial.run(windows_per_dispatch=16)
    want = _chain(serial)

    interrupted = build_simulation(_cfg(workers=4))
    interrupted.add_handoff_hook(lambda s, mn, gid: None, sharded=True)
    interrupted.configure_auto_checkpoint(
        str(tmp_path), every_ns=simtime.NS_PER_SEC
    )
    interrupted.run(until=3 * simtime.NS_PER_SEC,
                    windows_per_dispatch=16)
    assert interrupted.hostplane_stats()["sharded_drains"] > 0
    del interrupted  # the SIGKILL: nothing survives but the ring

    res = build_simulation(_cfg(workers=4))
    res.add_handoff_hook(lambda s, mn, gid: None, sharded=True)
    res.resume_from(str(tmp_path))
    res.run(windows_per_dispatch=16)
    assert _chain(res) == want


# ---------------------------------------------------------------------------
# worker exception -> serial fallback, canonical order preserved
# ---------------------------------------------------------------------------


def test_worker_exception_falls_back_serially(serial_ref):
    sim = build_simulation(_cfg(workers=4))
    blown = []

    def fragile(s, mn, gid):
        # raises exactly once, on a worker thread; the coordinator's
        # canonical-order re-run must succeed and keep the chain
        if not blown:
            blown.append(gid)
            raise RuntimeError("worker boom")

    sim.add_handoff_hook(fragile, sharded=True)
    sim.run(windows_per_dispatch=16)
    assert _chain(sim) == serial_ref[0]
    st = sim.hostplane_stats()
    assert st["serial_fallbacks"] >= 1
    assert blown  # the exception actually fired


def test_fallback_merge_order_stays_canonical():
    """A failed action's coordinator re-run merges IN PLACE in the
    canonical walk — not appended after the survivors."""
    st = hostplane_mod.new_stats(2)
    plane = hostplane_mod.HostPlane(2, st)
    merged = []
    armed = [True]

    def work(g):
        if g == 1 and armed:
            armed.clear()
            raise RuntimeError("boom")
        return g

    acts = [
        hostplane_mod.HostAction(0, g, (lambda g=g: work(g)), merged.append)
        for g in (3, 1, 0, 2)
    ]
    assert plane.drain(acts) == 4
    plane.close()
    assert merged == [0, 1, 2, 3]  # canonical despite the gid-1 failure
    assert st["serial_fallbacks"] == 1


# ---------------------------------------------------------------------------
# the canonical runnable-queue key (procs/driver.py)
# ---------------------------------------------------------------------------


def test_runnable_queue_pops_in_canonical_order():
    """The managed plane's runnable queue orders by the host plane's
    merge key — (virtual time at mark, owning host gid, mark seq) — not
    by registration index or insertion order."""
    from shadow_tpu.procs.driver import ProcessDriver

    class _Host:
        def __init__(self, index):
            self.index = index

    class _Proc:
        def __init__(self, reg_idx, gid):
            self.reg_idx = reg_idx
            self.host = _Host(gid)

    drv = ProcessDriver(stop_time=1, seed=1)
    # scrambled insertion at t=0: gids 5, 2, 9, 2 (high reg_idx first)
    for reg_idx, gid in ((40, 5), (30, 2), (20, 9), (10, 2)):
        drv._mark_runnable(_Proc(reg_idx, gid))
    drv.now = 7
    drv._mark_runnable(_Proc(50, 0))  # later vt loses to earlier vt

    popped = []
    while drv._runq_heap:
        t, gid, seq, idx = heapq.heappop(drv._runq_heap)
        popped.append((t, gid, idx))
    assert popped == [
        (0, 2, 30), (0, 2, 10),   # gid ties break by mark seq
        (0, 5, 40), (0, 9, 20),
        (7, 0, 50),               # virtual time dominates gid
    ]


# ---------------------------------------------------------------------------
# hostplane.* telemetry (metrics schema v15)
# ---------------------------------------------------------------------------


def test_hostplane_metrics_schema_v15(tmp_path):
    sim = build_simulation(_cfg(workers=4))
    sim.add_handoff_hook(lambda s, mn, gid: None, sharded=True)
    sim.run(windows_per_dispatch=16)
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(str(tmp_path / "m.json"))
    assert_current_metrics_schema(doc)
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    c = doc["counters"]
    assert c["hostplane.workers"] == 4
    assert c["hostplane.sharded_drains"] > 0
    assert c["hostplane.serial_fallbacks"] == 0
    assert c["hostplane.pin_moves"] == 0
    assert sum(c[f"hostplane.drain_ns_w{w}"] for w in range(4)) >= 0


def test_serial_run_emits_no_hostplane_keys(tmp_path):
    sim = build_simulation(_cfg(workers=1))
    sim.add_handoff_hook(lambda s, mn, gid: None, sharded=True)
    sim.run(windows_per_dispatch=16)
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(str(tmp_path / "m.json"))
    assert not [k for k in doc["counters"] if k.startswith("hostplane.")]
    assert not [k for k in doc["gauges"] if k.startswith("hostplane.")]


def test_config_rejects_bad_host_workers():
    from shadow_tpu.core.config import ConfigError, load_config

    with pytest.raises(ConfigError):
        load_config(_cfg(workers=0))
    cfg = load_config(_cfg(workers=3))
    assert cfg.experimental.host_workers == 3
