"""The CPU↔TPU seam (procs/bridge.py): REAL processes exchange UDP through
the device-stepped network — NIC token buckets, CoDel router, path
latency/loss all computed by the window kernel (the BASELINE north star).
"""

import pytest

from shadow_tpu.procs import build as build_mod
from shadow_tpu.procs.builder import build_process_driver

pytestmark = pytest.mark.skipif(
    not build_mod.toolchain_available(), reason="no native toolchain"
)

NS_PER_MS = 1_000_000


def _yaml(apps, lat_ms, loss=0.0, count=2):
    return f"""
general:
  stop_time: 30 s
  seed: 12
network:
  graph:
    type: gml
    inline: |
      graph [
        node [ id 0 bandwidth_down "100 Mbit" bandwidth_up "100 Mbit" ]
        edge [ source 0 target 0 latency "{lat_ms} ms" packet_loss {loss} ]
      ]
experimental:
  use_device_network: true
  event_capacity: 2048
  events_per_host_per_window: 8
hosts:
  server:
    processes:
      - path: {apps['udp_echo_server']}
        args: 9000 {count}
  client:
    processes:
      - path: {apps['udp_echo_client']}
        args: server 9000 {count}
        start_time: 1 s
"""


def test_udp_echo_through_device_network(apps):
    """RTTs observed by the real client equal 2 x the GML edge latency on
    the virtual clock — the deliveries were timed by the device kernel."""
    d = build_process_driver(_yaml(apps, lat_ms=25))
    assert d.bridge is not None
    d.run()
    client, server = d.procs  # hosts are name-sorted: client before server
    assert client.exit_code == 0, client.stderr
    assert server.exit_code == 0, server.stderr
    rtts = [int(l.split()[1]) for l in client.stdout.decode().splitlines()
            if l.startswith("rtt")]
    assert rtts == [2 * 25 * NS_PER_MS] * 2, rtts
    # the device actually carried the packets
    c = d.bridge.sim.counters()
    assert c["packets_delivered"] == 4
    assert d.bridge.sim.host_trackers()["tx_packets"].sum() == 4


def test_bridge_deterministic(apps):
    """Byte-identical reruns with the device network in the loop."""
    def run_once():
        d = build_process_driver(_yaml(apps, lat_ms=10))
        d.run()
        return [p.stdout for p in d.procs]

    assert run_once() == run_once()


def test_bridge_loss_applies_on_device(apps):
    """With a lossy edge, the device's reliability roll drops packets; the
    client blocks and is stopped at sim end (no crash, deterministic)."""
    d = build_process_driver(_yaml(apps, lat_ms=5, loss=0.7, count=6))
    d.run()
    c = d.bridge.sim.counters()
    assert c["packets_dropped_loss"] > 0
