"""Benchmark: on-device PHOLD throughput vs a CPU sequential-DES baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The workload is the PHOLD PDES canary (reference src/test/phold/phold.yaml:
peers over a 50ms self-loop link exchanging random-destination messages),
scaled up. `value` is committed events/sec on the device for the full fused
run (one XLA while_loop program). `vs_baseline` is the speedup over the
reference-replica C++ scheduler (native/baseline/phold_baseline.cpp): the
reference itself cannot build in this image (its config/worker layer needs
cargo/rustc, plus glib and igraph — none present, zero egress), so the
replica reimplements its exact hot path — per-host locked priority queues,
worker threads, conservative windows, (time,dst,src,seq) total order — in
C++ at -O2 and runs the same PHOLD workload on this machine's CPU.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time


# Probe timeline of the last wait_for_backend call: one entry per attempt.
# On probe-budget exhaustion this rides the structured failure artifact so
# BENCH rounds stay machine-parseable (r03-r05 recorded rc=1 text tails
# only) — see main().
_PROBE_LOG: list[dict] = []


def wait_for_backend(max_wait_s: float = 1500.0, probe_timeout_s: float = 240.0):
    """Block until the accelerator backend answers a trivial dispatch.

    Round 3 ended with BENCH recording rc=1 because the TPU worker was down
    at capture time and the bench burned its one attempt on a dead backend.
    Probe in a SUBPROCESS (a hung backend must not hang the bench), retry
    with jittered exponential backoff up to max_wait_s, and return
    True/False rather than raising so callers can decide what a dead
    backend costs them. Each attempt is recorded in _PROBE_LOG for the
    failure artifact.

    Budget accounting (BENCH_r05: probe 6 launched with 84 s of budget and
    overran to −166 s): every probe's subprocess timeout is CLAMPED to the
    remaining budget, so exhaustion is detected on time, never a full
    probe_timeout_s late. The sleep between probes is jittered exponential
    (not a fixed interval), so a fleet of benches never hammers a
    recovering worker in lockstep.
    """
    deadline = time.monotonic() + max_wait_s
    attempt = 0
    backoff_s = 10.0
    _PROBE_LOG.clear()
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        # the FINAL probe never overruns the budget (min floor keeps a
        # probe long enough to boot a healthy backend)
        timeout_s = min(probe_timeout_s, max(5.0, remaining))
        t0 = time.monotonic()
        try:
            # The probe must verify WHICH platform answered: with the TPU
            # worker down, jax silently falls back to CPU and a naive
            # probe would wave the bench through to record CPU numbers as
            # device results.
            allow_cpu = os.environ.get("SHADOW_TPU_BENCH_ALLOW_CPU") == "1"
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax, jax.numpy as jnp;"
                 "jnp.ones(8).sum().block_until_ready();"
                 "print('BACKEND_OK', jax.default_backend(),"
                 " len(jax.devices()))"],
                timeout=timeout_s, capture_output=True, text=True,
            )
            if proc.returncode == 0 and "BACKEND_OK" in proc.stdout:
                platform = proc.stdout.split("BACKEND_OK", 1)[1].split()[0]
                if platform != "cpu" or allow_cpu:
                    _PROBE_LOG.append({
                        "attempt": attempt, "ok": True,
                        "wall_s": round(time.monotonic() - t0, 1),
                        "platform": platform,
                    })
                    return True
                err = f"only CPU backend available (got {platform!r})"
            else:
                err = (proc.stdout + proc.stderr)[-300:]
        except subprocess.TimeoutExpired:
            err = f"probe timed out after {timeout_s:.0f}s"
        remaining = deadline - time.monotonic()
        _PROBE_LOG.append({
            "attempt": attempt, "ok": False,
            "wall_s": round(time.monotonic() - t0, 1),
            "timeout_s": round(timeout_s, 1),
            "error": str(err)[-300:],
        })
        print(
            f"# backend probe {attempt} failed ({time.monotonic()-t0:.0f}s): "
            f"{err!r}; {remaining:.0f}s of retry budget left",
            file=sys.stderr, flush=True,
        )
        if remaining <= 0:
            return False
        # jittered exponential backoff (±50%), clamped to the budget
        time.sleep(min(remaining, backoff_s * (0.5 + random.random())))
        backoff_s = min(backoff_s * 2, 120.0)


class BackendUnavailable(RuntimeError):
    """The backend died mid-run and the probe budget is exhausted: the
    round's result is the structured ok:false artifact, not a traceback
    (r05 recorded rc:1 on this path; main() now records rc 0 here too)."""


def _with_backend_retry(fn, *args, **kw):
    """Run one benchmark stage; if the backend dies mid-run (worker crash,
    tunnel drop), wait for it to come back and retry ONCE."""
    from shadow_tpu.core.supervisor import BACKEND_LOST, classify_failure

    try:
        return fn(*args, **kw)
    except RuntimeError as e:
        if classify_failure(e) != BACKEND_LOST:
            raise
        print(f"# stage hit backend failure: {e!r}; waiting for recovery",
              file=sys.stderr, flush=True)
        # Drop the parent's (poisoned) PJRT client FIRST: on a locally
        # attached TPU the probe subprocess could never acquire the device
        # while this process still holds it, and the retry must reconnect
        # through a fresh client either way.
        try:
            import jax

            jax.clear_backends()
        except Exception as reset_err:  # best effort
            print(f"# backend reset failed: {reset_err!r}", file=sys.stderr)
        if not wait_for_backend():
            raise BackendUnavailable(str(e)) from e
        return fn(*args, **kw)


def _enable_compile_cache():
    """Persistent XLA compile cache: the staged configs compile multi-minute
    programs; cache them so reruns start in seconds. The root is SHARED
    with the serve daemon's AOT kernel cache (shadow_tpu/serve/kcache.py
    cache_root: $SHADOW_TPU_CACHE_DIR, else .jax_cache next to the repo),
    so daemon and bench warm each other. Corrupt/zero-length entries —
    the residue of a process killed mid-write — are evicted up front
    instead of letting JAX raise when it deserializes one mid-run."""
    from shadow_tpu.serve.kcache import enable_xla_cache

    cache, evicted = enable_xla_cache()
    if evicted:
        print(f"# compile cache: evicted {evicted} corrupt entr"
              f"{'y' if evicted == 1 else 'ies'} from {cache}",
              file=sys.stderr)


_enable_compile_cache()


def device_phold(num_hosts: int, msgload: int, stop_s: int,
                 windows_per_dispatch: int = 64, num_shards: int = 1):
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.flagship import build_phold_flagship

    sim = build_phold_flagship(
        num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s,
        num_shards=num_shards,
    )
    # Warm-up compile (cached), then timed run.
    sim.run(until=int(0.2 * simtime.NS_PER_SEC),
            windows_per_dispatch=windows_per_dispatch)
    jax.block_until_ready(sim.state.pool.time)
    t0 = time.perf_counter()
    sim.run(windows_per_dispatch=windows_per_dispatch)
    jax.block_until_ready(sim.state.pool.time)
    wall = time.perf_counter() - t0
    c = sim.counters()
    return c["events_committed"], wall, stop_s / wall


_REPO = os.path.dirname(os.path.abspath(__file__))
_BASELINE_SRC = os.path.join(_REPO, "native", "baseline", "phold_baseline.cpp")
_BASELINE_BIN = os.path.join(_REPO, "native", "build", "phold_baseline")


def cpp_phold_baseline(num_hosts: int, msgload: int, stop_s: int,
                       workers: int = 0):
    """Run the reference-replica C++ scheduler (see module docstring) on the
    same PHOLD parameters; returns its parsed JSON result. workers=0 means
    one per online CPU (the reference's recommended parallelism,
    configuration.rs:141-147)."""
    if not os.path.exists(_BASELINE_BIN) or (
        os.path.getmtime(_BASELINE_BIN) < os.path.getmtime(_BASELINE_SRC)
    ):
        os.makedirs(os.path.dirname(_BASELINE_BIN), exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-pthread", "-o", _BASELINE_BIN, _BASELINE_SRC],
            check=True,
        )
    # runtime == stop: hosts forward for the whole run, matching
    # device_phold's build (runtime_s=stop_s).
    out = subprocess.run(
        [_BASELINE_BIN, str(num_hosts), str(msgload), "50", str(stop_s),
         str(stop_s), str(workers), "42"],
        check=True, capture_output=True, text=True,
    )
    return json.loads(out.stdout)


def _run_stage(stage: str, app_model: str, loss: float, app_options: dict,
               extra_counters: tuple = (), num_hosts: int = 10240,
               stop_s: int = 4, event_capacity: int = 1 << 15,
               extra_experimental: dict | None = None,
               windows_per_dispatch: int = 8, num_shards: int = 1,
               sync: str = "conservative"):
    """Build, warm up (compile + bootstrap), then time the remaining sim
    span. Warm-up-committed events are subtracted so the reported rate and
    sim/wall ratio cover only the timed segment."""
    import jax

    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.sim import build_simulation

    warmup_ns = 1_500_000_000
    n_servers = num_hosts // 8
    cfg = {
        "general": {"stop_time": stop_s, "seed": 7},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]\n'
            f'  edge [ source 0 target 0 latency "10 ms" packet_loss {loss} ]\n'
            ']\n')}},
        # Pool capacity sized to the stage's in-flight population (timers +
        # packets in transit): oversizing it is pure waste — the per-window
        # pool sort is the dominant cost and scales with capacity.
        "experimental": {
            "event_capacity": event_capacity,
            "events_per_host_per_window": 16,
            "outbox_slots": 16,
            # ring/inbox capacities sized to the stage's queue depths:
            # every slot is a full [H, slots, P] write per update, so
            # oversizing is pure memory traffic
            "router_queue_slots": 16,
            "inbox_slots": 4,
            **({"num_shards": num_shards} if num_shards > 1 else {}),
            **(extra_experimental or {}),
        },
        "hosts": {
            "server": {"quantity": n_servers, "app_model": app_model,
                       "app_options": {"role": "server"}},
            "client": {"quantity": num_hosts - n_servers,
                       "app_model": app_model, "app_options": app_options},
        },
    }
    sim = build_simulation(cfg)
    # Telemetry session: wall histograms + per-round throughput ride the
    # handoff boundaries the driver already syncs at; the device counter
    # block is compiled into the kernel either way.
    session = obs_metrics.ObsSession()
    sim.obs_session = session
    # Bounded dispatch chunks: minutes-long single dispatches can crash the
    # accelerator runtime's watchdog at this scale, but each dispatch costs
    # ~8 ms of tunnel overhead (profiled), so size them as large as safe.
    windows = rollbacks = None
    if sync == "optimistic":
        # BASELINE config 4's sync mode: adaptive speculative windows
        # (engine.run_optimistic); warm-up compiles the attempt kernel
        sim.run_optimistic(until=warmup_ns)
        jax.block_until_ready(sim.state.pool.time)
        warm_events = sim.counters()["events_committed"]
        t0 = time.perf_counter()
        # timed-segment counts only, consistent with events_per_sec
        windows, rollbacks = sim.run_optimistic()
        jax.block_until_ready(sim.state.pool.time)
        wall = time.perf_counter() - t0
    else:
        sim.run(until=warmup_ns, windows_per_dispatch=windows_per_dispatch)
        jax.block_until_ready(sim.state.pool.time)
        warm_events = sim.counters()["events_committed"]
        t0 = time.perf_counter()
        sim.run(windows_per_dispatch=windows_per_dispatch)
        jax.block_until_ready(sim.state.pool.time)
        wall = time.perf_counter() - t0
    c = sim.counters()
    timed_events = c["events_committed"] - warm_events
    timed_sim_s = stop_s - warmup_ns / 1e9
    spill_st = sim.spill_stats()
    out = {
        "stage": stage,
        "hosts": num_hosts,
        "num_shards": num_shards,
        "sync": sync,
        "events_per_sec": round(timed_events / wall, 1),
        "packets_delivered": c["packets_delivered"],
        "sim_sec_per_wall_sec": round(timed_sim_s / wall, 2),
        # must stay 0 or the measurement dropped work
        "pool_overflow_dropped": c["pool_overflow_dropped"],
    }
    if windows is not None:
        out["windows"] = windows
        out["rollbacks"] = rollbacks
    if spill_st.get("spill_episodes"):
        out.update(spill_st)  # the never-drop tier fired: record its cost
    for k in extra_counters:
        out[k] = c[k]
    # compact telemetry sub-object: the signals every perf comparison
    # needs, pulled from the device block + wall histograms (the full
    # document is what --metrics-out dumps)
    session.finalize(sim)
    doc = session.metrics.to_doc()
    hist = doc["histograms"]
    gear = sim.gear_stats()
    out["metrics"] = {
        # which backend actually ran the row: a TPU-worker outage silently
        # falls back to CPU, and a result row must be attributable
        "platform": jax.default_backend(),
        "windows_run": doc["counters"].get("obs.windows_run", 0),
        "matrix_dispatches": doc["counters"].get("obs.matrix_dispatches", 0),
        "loop_dispatches": doc["counters"].get("obs.loop_dispatches", 0),
        "window_shrinks": doc["counters"].get("obs.window_shrinks", 0),
        "vtime_spread_ns": doc["gauges"].get("vtime.spread_ns", 0),
        "dispatch_p50_s": round(
            hist.get("wall.dispatch_s", {}).get("p50", 0.0), 4
        ),
        "round_events_per_sec_p50": round(
            hist.get("round.events_per_sec", {}).get("p50", 0.0), 1
        ),
        # gearbox telemetry (core/gearbox.py): active level, shift count,
        # and the per-gear dispatch histogram
        "gear_level": gear["gear_level"],
        "gear_tiers": gear["gear_tiers"],
        "gear_shifts": gear["gear_shifts"],
        "gear_dispatches": gear["gear_dispatches"],
    }
    return out


def stage_udp_flood(num_hosts: int = 10240, stop_s: int = 4):
    """BASELINE staged config 2: 10k-host UDP flood through the full device
    network stack (NIC token buckets, CoDel router, UDP sockets)."""
    # Shapes tuned from the on-chip profile (tools/profile_flood.py): the
    # extraction/merge sorts carry C + H*(K+1) rows (+ H*(O+B) box rows in
    # the merge) and are ~60% of device time — K/O/C are sized to the
    # workload's Poisson tails, no further.
    return _run_stage(
        "udp_flood_10k", "udp_flood", 0.001,
        {"interval": "20 ms", "size": 1024, "runtime": stop_s - 1},
        # 1 << 14 pool capacity measurably overflows (1.5k drops); 1 << 15
        # does not
        num_hosts=num_hosts, stop_s=stop_s, event_capacity=1 << 15,
        extra_experimental={"events_per_host_per_window": 12,
                            "outbox_slots": 8},
        windows_per_dispatch=32,
    )


def stage_tcp_bulk(num_hosts: int = 10240, stop_s: int = 4):
    """BASELINE staged config 3: 10k-host TCP bulk transfer (vmap'd
    handshake + seq/ack + Reno congestion state machines)."""
    return _run_stage(
        "tcp_bulk_10k", "tcp_bulk", 0.0005, {"total": "64 KiB"},
        extra_counters=("bytes_delivered",),
        # in-flight population ~25 events/client (cwnd segments + ACKs +
        # pump/timer events): 1 << 16 measurably overflows, 1 << 18 does not
        num_hosts=num_hosts, stop_s=stop_s, event_capacity=1 << 18,
        # TCP self-events (timers + pumps) need more inbox headroom than
        # the UDP stage; the TCP handler suite's worst-case emission count
        # per event is 28 (engine probe), so the outbox must cover it —
        # O=16 fails the build-time probe (this is what blocked the r2
        # stage-3 recording)
        extra_experimental={"inbox_slots": 8, "outbox_slots": 32},
    )


def stage_phold_100k(stop_s: int = 10):
    """BASELINE staged configs 4-5 shape probe: 100k hosts on ONE chip
    (matrix fast path). msgload 2 → 20M+ committed events. SHORT dispatch
    chunks: at this scale a 64-window dispatch runs long enough to trip
    the accelerator runtime's watchdog and crash the worker."""
    num_hosts, msgload = 100_000, 2
    events, wall, sim_per_wall = device_phold(
        num_hosts, msgload, stop_s, windows_per_dispatch=4
    )
    base = cpp_phold_baseline(num_hosts, msgload, stop_s)
    rate = events / wall if wall > 0 else 0.0
    return {
        "stage": "phold_100k",
        "hosts": num_hosts,
        "events_per_sec": round(rate, 1),
        "sim_sec_per_wall_sec": round(sim_per_wall, 2),
        "vs_baseline": round(rate / (base["events_per_sec"] or 1.0), 3),
    }


def stage_udp_flood_50k(sync: str = "conservative", stop_s: int = 3,
                        num_shards: int = 1):
    """BASELINE staged config 4 shape: 50k hosts through the full device
    network stack, in BOTH sync modes (config 4 pairs this scale with
    optimistic PDES windows; conservative is the control row) — and, with
    num_shards > 1, on the ISLANDS runner in both modes (virtual islands
    batch the local sorts S× smaller; optimistic×islands is the round-5
    engine work)."""
    return _run_stage(
        "udp_flood_50k", "udp_flood", 0.001,
        {"interval": "40 ms", "size": 1024, "runtime": stop_s - 1},
        num_hosts=50176,  # 49 * 1024
        stop_s=stop_s, event_capacity=1 << 17,
        extra_experimental={"events_per_host_per_window": 12,
                            "outbox_slots": 8},
        windows_per_dispatch=16, sync=sync, num_shards=num_shards,
    )


def stage_spill_50k(stop_s: int = 3):
    """Deliberately undersized pool at the 50k shape (VERDICT r4 #6): the
    spill tier must complete the run — measure what the never-drop
    guarantee costs at scale (episodes, drained/injected rows, sim/wall vs
    the right-sized conservative row)."""
    return _run_stage(
        "udp_flood_50k_spill", "udp_flood", 0.001,
        {"interval": "40 ms", "size": 1024, "runtime": stop_s - 1},
        num_hosts=50176, stop_s=stop_s,
        # a quarter of the right-sized pool: guaranteed spill episodes
        event_capacity=1 << 15,
        extra_experimental={"events_per_host_per_window": 12,
                            "outbox_slots": 8},
        windows_per_dispatch=16,
    )


def stage_udp_flood_100k(stop_s: int = 3):
    """100k hosts through the full device network stack on one chip."""
    return _run_stage(
        "udp_flood_100k", "udp_flood", 0.001,
        {"interval": "40 ms", "size": 1024, "runtime": stop_s - 1},
        num_hosts=100_352,  # 98 * 1024: divisible for future mesh splits
        stop_s=stop_s, event_capacity=1 << 18,
    )


def stage_obs_overhead(num_hosts: int = 8192, msgload: int = 4,
                       stop_s: int = 4):
    """Telemetry-plane overhead smoke row (ISSUE 1 acceptance gate): the
    flagship PHOLD shape with the device counter block compiled IN vs OUT
    (experimental.obs_counters). The block costs one fused [NUM_WIN] add
    plus two [H] selects per window step; the gate is <= 3% step time."""
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.flagship import build_phold_flagship

    def timed(obs_on: bool) -> tuple[float, int]:
        sim = build_phold_flagship(
            num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s,
            obs_counters=obs_on,
        )
        sim.run(until=int(0.2 * simtime.NS_PER_SEC))
        jax.block_until_ready(sim.state.pool.time)
        t0 = time.perf_counter()
        sim.run()
        jax.block_until_ready(sim.state.pool.time)
        return time.perf_counter() - t0, sim.counters()["events_committed"]

    # interleave the arms to decorrelate machine drift from the comparison
    w_on = min(timed(True)[0] for _ in range(2))
    w_off, events = timed(False)
    w_off = min(w_off, timed(False)[0])
    overhead = (w_on - w_off) / w_off * 100.0 if w_off > 0 else 0.0
    return {
        "stage": "obs_overhead",
        "hosts": num_hosts,
        "events": int(events),
        "wall_obs_on_s": round(w_on, 3),
        "wall_obs_off_s": round(w_off, 3),
        "overhead_pct": round(overhead, 2),
        "gate_3pct": overhead <= 3.0,
    }


def stage_audit_smoke(num_hosts: int = 8192, msgload: int = 4,
                      stop_s: int = 4, flight_capacity: int = 64):
    """Determinism-audit gate (ISSUE 5 acceptance): the flagship PHOLD
    shape with the digest chain + flight ring compiled IN vs OUT — the
    folds are fused i64 arithmetic and one-hot ring writes per window
    step, gated at ≤ 3% step time. Also asserts the chain is identical
    across two seeded reruns, and that the divergence bisector pinpoints
    the exact forged window (the diff engine behind tools/diff_digest.py).
    Writes a schema-v5 metrics artifact (audit.* namespace) so
    tools/tpu_watch.py can schema-gate this stage line at capture."""
    import copy
    import tempfile

    import jax
    import numpy as np

    from shadow_tpu.core import simtime
    from shadow_tpu.flagship import build_phold_flagship
    from shadow_tpu.obs import audit as audit_mod
    from shadow_tpu.obs import metrics as obs_metrics

    def timed(audit_on: bool, flight: int, seed: int = 42):
        sim = build_phold_flagship(
            num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s,
            seed=seed, audit_digest=audit_on, flight_recorder=flight,
        )
        sim.run(until=int(0.2 * simtime.NS_PER_SEC))
        jax.block_until_ready(sim.state.pool.time)
        t0 = time.perf_counter()
        sim.run()
        jax.block_until_ready(sim.state.pool.time)
        return time.perf_counter() - t0, sim

    # interleave the arms to decorrelate machine drift from the comparison
    w_aud, sim1 = timed(True, flight_capacity)
    w_base, _ = timed(False, 0)
    w2, sim2 = timed(True, flight_capacity)
    w_aud = min(w_aud, w2)
    w_base = min(w_base, timed(False, 0)[0])
    overhead = (w_aud - w_base) / w_base * 100.0 if w_base > 0 else 0.0
    chain1, chain2 = sim1.audit_chain(), sim2.audit_chain()

    # divergence bisection: two seeded reruns dump identical digest docs;
    # forging one mid-run record (and one host sub-chain) must be
    # pinpointed to the exact window and host
    tiny = dict(num_hosts=1024, msgload=2, stop_s=2, runtime_s=2)
    with tempfile.TemporaryDirectory(prefix="audit_smoke_") as td:
        docs = []
        for i in range(2):
            s = build_phold_flagship(audit_digest=True, **tiny)
            s.attach_audit(meta={"arm": i})
            s.run(windows_per_dispatch=4)
            docs.append(s.write_digest(os.path.join(td, f"d{i}.json")))
    clean = audit_mod.diff_digest_docs(docs[0], docs[1])
    forged = copy.deepcopy(docs[1])
    k = len(forged["records"]) // 2
    forged["records"][k]["chain"] ^= 0x5A5A
    forged["hosts"][3] = (forged["hosts"][3] ^ 0x5A5A) & ((1 << 64) - 1)
    forged["final"]["chain"] = audit_mod.combine(
        np.asarray(forged["hosts"], dtype=np.uint64)
    )
    rep = audit_mod.diff_digest_docs(docs[0], forged)
    first = rep["first_divergent_record"] or {}
    forged_found = (
        first.get("seq_a") == docs[0]["records"][k]["seq"]
        and rep["divergent_hosts"] == [3]
    )

    # schema-v5 metrics artifact with the audit.* namespace, referenced
    # from this row so tpu_watch schema-gates it at capture time
    metrics_path = os.path.join(_REPO, "audit_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(sim1)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "audit_smoke", "hosts": num_hosts,
    })
    obs_metrics.validate_metrics_doc(doc)

    gate_3 = overhead <= 3.0
    return {
        "stage": "audit_smoke",
        "hosts": num_hosts,
        "flight_capacity": flight_capacity,
        "wall_base_s": round(w_base, 3),
        "wall_audit_s": round(w_aud, 3),
        "overhead_pct": round(overhead, 2),
        "gate_3pct": gate_3,
        "chain": int(chain1),
        "chains_equal": chain1 == chain2 and chain1 != 0,
        "rerun_docs_identical": clean["identical"],
        "forged_window_found": forged_found,
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate": bool(
            gate_3 and chain1 == chain2 and chain1 != 0
            and clean["identical"] and forged_found
        ),
    }


def stage_gear_win(num_hosts: int = 8192, msgload: int = 4, stop_s: int = 4):
    """Gearing win smoke row (ISSUE 2 acceptance gate): the flagship PHOLD
    shape with the pool oversized 8× above steady-state occupancy — the
    burst-provisioned pool every production config carries — run fixed
    (pool_gears=1) vs geared (pool_gears=3, engages the C/4 tier). Gate:
    geared per-window wall time ≥ 25% better at occupancy ≤ C/4."""
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.flagship import build_phold_flagship

    # live population = num_hosts * msgload; capacity 8x above it
    capacity = 8 * num_hosts * msgload

    def timed(gears: int):
        sim = build_phold_flagship(
            num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s,
            event_capacity=capacity, pool_gears=gears,
        )
        sim.run(until=int(0.2 * simtime.NS_PER_SEC))
        jax.block_until_ready(sim.state.pool.time)
        t0 = time.perf_counter()
        sim.run()
        jax.block_until_ready(sim.state.pool.time)
        wall = time.perf_counter() - t0
        snap = sim.obs_snapshot()
        windows = snap["win"]["windows_run"] if snap else 0
        return wall, windows, sim.counters()["events_committed"], \
            sim.gear_stats()

    # interleave the arms to decorrelate machine drift from the comparison
    w_fix, n_fix, ev_fix, _ = timed(1)
    w_gear, n_gear, ev_gear, gear = timed(3)
    w_fix = min(w_fix, timed(1)[0])
    w_gear = min(w_gear, timed(3)[0])
    per_win_fix = w_fix / max(n_fix, 1)
    per_win_gear = w_gear / max(n_gear, 1)
    win_pct = (1.0 - per_win_gear / per_win_fix) * 100.0 if per_win_fix else 0.0
    return {
        "stage": "gear_win",
        "hosts": num_hosts,
        "pool_capacity": capacity,
        "occupancy": num_hosts * msgload,
        "events_fixed": int(ev_fix),
        "events_geared": int(ev_gear),
        "events_equal": ev_fix == ev_gear,
        "windows_fixed": int(n_fix),
        "windows_geared": int(n_gear),
        "wall_fixed_s": round(w_fix, 3),
        "wall_geared_s": round(w_gear, 3),
        "per_window_fixed_ms": round(per_win_fix * 1e3, 4),
        "per_window_geared_ms": round(per_win_gear * 1e3, 4),
        "win_pct": round(win_pct, 2),
        "gate_25pct": win_pct >= 25.0,
        "gear": gear,
    }


def stage_fault_smoke():
    """Fault-plane smoke row (ISSUE 3 acceptance gate): a quarantine-mode
    managed-process run with ONE injected kill_proc mid-run must complete
    with rc=0 (the unaffected pair finishes; the faulted process is
    excluded from plugin-error accounting) and record faults.* metrics
    (hosts_quarantined, injections_fired)."""
    import contextlib
    import io
    import pathlib
    import shutil
    import tempfile

    from shadow_tpu.procs import build as build_mod

    if not build_mod.toolchain_available():
        return {"stage": "fault_smoke", "error": "no native toolchain",
                "gate_rc0": False, "gate_metrics": False}

    tmp = tempfile.mkdtemp(prefix="shadow_tpu_fault_smoke_")
    try:
        cc = shutil.which("cc") or shutil.which("gcc")
        apps = {}
        for stem in ("udp_echo_server", "udp_echo_client"):
            src = pathlib.Path(_REPO) / "tests" / "apps" / f"{stem}.c"
            exe = pathlib.Path(tmp) / stem
            subprocess.run(
                [cc, "-O1", "-o", str(exe), str(src), "-lpthread"],
                check=True, capture_output=True,
            )
            apps[stem] = str(exe)

        from shadow_tpu.__main__ import _run_process_plane
        from shadow_tpu.core.config import load_config
        from shadow_tpu.procs.builder import build_process_driver

        gml = (
            'graph [\n'
            '  node [ id 0 bandwidth_down "100 Mbit" '
            'bandwidth_up "100 Mbit" ]\n'
            '  edge [ source 0 target 0 latency "50 ms" '
            'packet_loss 0.0 ]\n'
            ']\n'
        )
        # pair A completes normally; pair B's client (40 pings x 100 ms
        # RTT: busy until ~5 s) is killed at 3 s and its host quarantined
        cfg = load_config({
            "general": {"stop_time": "6 s", "seed": 7},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "faults": {
                "on_proc_failure": "quarantine",
                "inject": [
                    {"at": "3 s", "op": "kill_proc", "proc": "clientb.0"},
                ],
            },
            "hosts": {
                "servera": {"processes": [
                    {"path": apps["udp_echo_server"], "args": "9000 3"}]},
                "clienta": {"processes": [
                    {"path": apps["udp_echo_client"],
                     "args": "servera 9000 3", "start_time": "1 s"}]},
                "serverb": {"processes": [
                    {"path": apps["udp_echo_server"], "args": "9000 40"}]},
                "clientb": {"processes": [
                    {"path": apps["udp_echo_client"],
                     "args": "serverb 9000 40", "start_time": "1 s"}]},
            },
        })
        driver = build_process_driver(
            cfg, data_root=pathlib.Path(tmp) / "data"
        )
        out = io.StringIO()
        with contextlib.redirect_stdout(out), contextlib.redirect_stderr(out):
            rc = _run_process_plane(cfg, driver, False)
        stats = {k: int(v) for k, v in sorted(driver.fault_stats().items())}
        return {
            "stage": "fault_smoke",
            "rc": rc,
            "faults": stats,
            "gate_rc0": rc == 0,
            "gate_metrics": (
                stats.get("hosts_quarantined", 0) >= 1
                and stats.get("injections_fired", 0) >= 1
            ),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _fleet_smoke_job(seed: int, stop_s: float, num_hosts: int,
                     msgload: int) -> dict:
    """One fleet-smoke experiment config: the flagship PHOLD shape at a
    small host count (compile cost dominates solo runs at this scale,
    which is exactly the cost the fleet amortizes)."""
    from shadow_tpu.flagship import SELF_LOOP_50MS_GML

    return {
        "general": {"stop_time": f"{stop_s} s", "seed": seed},
        "network": {"graph": {"type": "gml", "inline": SELF_LOOP_50MS_GML}},
        "experimental": {
            "event_capacity": max(3 * num_hosts * msgload // 2, 4096),
            "events_per_host_per_window": msgload + 16,
            "outbox_slots": msgload + 16,
            "inbox_slots": 4,
        },
        "hosts": {
            "peer": {
                "quantity": num_hosts,
                "app_model": "phold",
                # runtime fixed across jobs (it compiles into the handler);
                # mixed LENGTH comes from general.stop_time
                "app_options": {"msgload": msgload, "runtime": 1},
            }
        },
    }


def stage_fleet_smoke(jobs: int = 8, num_hosts: int = 256, msgload: int = 2):
    """Fleet gate (ISSUE 4 acceptance): a fleet of `jobs` small mixed-
    length PHOLD experiments must compile ONE window kernel (asserted via
    the fleet's trace-count metric) and beat the summed wall-clock of the
    same experiments run solo — on CPU, where the win is compile/trace
    amortization plus dispatch batching."""
    import jax

    from shadow_tpu.fleet import JobSpec, build_fleet
    from shadow_tpu.sim import build_simulation

    stops = [1.5 + 0.5 * (i % 4) for i in range(jobs)]  # 1.5 .. 3.0 s
    cfgs = [
        _fleet_smoke_job(seed=100 + i, stop_s=stops[i],
                         num_hosts=num_hosts, msgload=msgload)
        for i in range(jobs)
    ]

    # solo arm: each experiment pays its own build + trace/compile + run
    solo_walls = []
    solo_events = []
    for cfg in cfgs:
        t0 = time.perf_counter()
        sim = build_simulation(cfg)
        sim.run()
        jax.block_until_ready(sim.state.pool.time)
        solo_walls.append(time.perf_counter() - t0)
        solo_events.append(sim.counters()["events_committed"])

    # fleet arm: one vmapped program, jobs swap through the lanes
    t0 = time.perf_counter()
    fleet = build_fleet(
        [JobSpec(name=f"smoke{i:02d}", config=cfgs[i]) for i in range(jobs)]
    )
    fleet.run()
    jax.block_until_ready(fleet.state.pool.time)
    fleet_wall = time.perf_counter() - t0

    rows = fleet.results()
    events_equal = [
        r["events_committed"] == e for r, e in zip(rows, solo_events)
    ]
    solo_sum = sum(solo_walls)
    traces = fleet.fleet_stats()["kernel_traces"]
    return {
        "stage": "fleet_smoke",
        "platform": jax.default_backend(),
        "jobs": jobs,
        "hosts": num_hosts,
        "stops_s": stops,
        "solo_wall_sum_s": round(solo_sum, 3),
        "fleet_wall_s": round(fleet_wall, 3),
        "speedup": round(solo_sum / fleet_wall, 2) if fleet_wall else 0.0,
        "kernel_traces": traces,
        "events_equal": all(events_equal),
        "jobs_done": fleet.fleet_stats()["jobs_done"],
        "gate_one_compile": traces == 1,
        "gate_wall": fleet_wall < solo_sum,
    }


def shard_sweep(shards=(1, 2, 4, 8), out_path: str | None = None):
    """Virtual-islands scaling sweep on ONE chip (VERDICT r4 gate 1c):
    PHOLD 16k and udp_flood_10k at each shard count; one JSON line each.
    Writes docs/shard_sweep.json for tools/plot_shards.py."""
    results = []
    for s in shards:
        ev, wall, spw = _with_backend_retry(
            device_phold, 16384, 8, 10, 64, s
        )
        r = {"stage": "phold_16k", "num_shards": s,
             "events_per_sec": round(ev / wall, 1),
             "sim_sec_per_wall_sec": round(spw, 2)}
        print(json.dumps(r), flush=True)
        results.append(r)
    for s in shards:
        r = _with_backend_retry(
            _run_stage,
            f"udp_flood_10k", "udp_flood", 0.001,
            {"interval": "20 ms", "size": 1024, "runtime": 3},
            num_hosts=10240, stop_s=4, event_capacity=1 << 15,
            extra_experimental={"events_per_host_per_window": 12,
                                "outbox_slots": 8},
            windows_per_dispatch=32, num_shards=s,
        )
        print(json.dumps(r), flush=True)
        results.append(r)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(results, f, indent=1)
    return results


def stage_resilience_smoke(num_hosts: int = 1024, msgload: int = 2,
                           stop_s: int = 2):
    """Backend-survivability gate (ISSUE 6 acceptance): a deterministic
    `kill_backend` injection mid-run must (a) drain to a crash-consistent
    checkpoint whose resumed run ends on the uninterrupted run's exact
    audit digest chain, and (b) complete in-process under
    `--on-backend-loss cpu` with the same chain, with the failover's wall
    overhead recorded. Writes a schema-v6 metrics artifact carrying the
    resilience.* namespace so tools/tpu_watch.py schema-gates the line at
    capture. CPU-deterministic by design (the injection IS the outage)."""
    import tempfile

    import jax

    from shadow_tpu.core.supervisor import BackendLost, BackendSupervisor
    from shadow_tpu.faults import plan as plan_mod
    from shadow_tpu.flagship import build_phold_flagship
    from shadow_tpu.obs import metrics as obs_metrics

    def build():
        return build_phold_flagship(
            num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s,
        )

    kill_at = [{"at": "1 s", "op": "kill_backend"}]

    # uninterrupted baseline
    t0 = time.perf_counter()
    ref = build()
    ref.run(windows_per_dispatch=4)
    jax.block_until_ready(ref.state.pool.time)
    wall_base = time.perf_counter() - t0
    base_chain = ref.audit_chain()
    base_events = ref.counters()["events_committed"]

    with tempfile.TemporaryDirectory(prefix="resilience_smoke_") as td:
        # (a) kill mid-run under policy abort: drain, then resume
        sim = build()
        sim.checkpoint_dir = td
        sim.attach_supervisor(BackendSupervisor(policy="abort"))
        sim.attach_faults(plan_mod.parse_fault_plan(kill_at))
        drained = False
        try:
            sim.run(windows_per_dispatch=4)
        except BackendLost:
            drained = True
        resumed = build()
        resumed.resume_from(td)
        resumed.run(windows_per_dispatch=4)
        resume_chain_equal = (
            drained and resumed.audit_chain() == base_chain
            and resumed.counters()["events_committed"] == base_events
        )

    # (b) kill under policy cpu: degraded-mode failover completes the run
    t0 = time.perf_counter()
    sim = build()
    sup = BackendSupervisor(policy="cpu", recheck_every=4)
    sim.attach_supervisor(sup)
    sim.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "kill_backend", "recover_after": 1}]
    ))
    sim.run(windows_per_dispatch=4)
    jax.block_until_ready(sim.state.pool.time)
    wall_failover = time.perf_counter() - t0
    failover_chain_equal = sim.audit_chain() == base_chain
    rstats = sim.resilience_stats()

    metrics_path = os.path.join(_REPO, "resilience_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "resilience_smoke", "hosts": num_hosts,
    })
    obs_metrics.validate_metrics_doc(doc)
    resilience_recorded = (
        doc["counters"].get("resilience.drains", 0) >= 1
        and doc["counters"].get("resilience.failovers", 0) >= 1
    )

    return {
        "stage": "resilience_smoke",
        "platform": jax.default_backend(),
        "hosts": num_hosts,
        "chain": int(base_chain),
        "wall_base_s": round(wall_base, 3),
        "wall_failover_s": round(wall_failover, 3),
        "failover_overhead_pct": round(
            (wall_failover - wall_base) / wall_base * 100.0, 2
        ) if wall_base > 0 else 0.0,
        "drained": drained,
        "resume_chain_equal": resume_chain_equal,
        "failover_chain_equal": failover_chain_equal,
        "resilience": {k: int(v) for k, v in sorted(rstats.items())},
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_resume": resume_chain_equal,
        "gate_failover": failover_chain_equal,
        "gate": bool(
            resume_chain_equal and failover_chain_equal
            and resilience_recorded
        ),
    }


def stage_pressure_smoke(num_hosts: int = 512, msgload: int = 4,
                         stop_s: int = 2):
    """Pressure-plane gate (ISSUE 9 acceptance): resource exhaustion must
    degrade deterministically instead of dying.

    (a) `exhaust_backend` mid-run: the classified RESOURCE_EXHAUSTED
        drives the degradation ladder (forced gear downshift overriding
        the red-zone rule, overflow parked on the host spill tier) and
        the run COMPLETES in-process with the uninterrupted run's exact
        audit digest chain.
    (b) the same injection with the ladder DISABLED reproduces the
        pre-ladder behavior: drain-to-checkpoint + a typed abort
        (BackendLost) — never a bare RuntimeError.
    (c) `saturate_pool` mid-window: sustained simulated pool pressure is
        absorbed by spill-tier escalation; the run completes with the
        exact chain where a stall used to raise.

    Writes a schema-v8 metrics artifact carrying the pressure.*
    namespace so tools/tpu_watch.py schema-gates the line at capture.
    CPU-deterministic by design (the injections ARE the pressure)."""
    import jax

    from shadow_tpu.core.pressure import (
        PressureController, PressurePolicy,
    )
    from shadow_tpu.core.supervisor import BackendLost, BackendSupervisor
    from shadow_tpu.faults import plan as plan_mod
    from shadow_tpu.flagship import build_phold_flagship
    from shadow_tpu.obs import metrics as obs_metrics

    def build():
        # occupancy (H x msgload) lands the build at the TOP gear, so the
        # ladder's forced downshift has a smaller tier to retreat to
        return build_phold_flagship(
            num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s,
            pool_gears=2,
        )

    def quiet_supervisor(policy="wait"):
        return BackendSupervisor(policy, sleep=lambda s: None)

    # uninterrupted baseline
    t0 = time.perf_counter()
    ref = build()
    ref.run(windows_per_dispatch=4)
    jax.block_until_ready(ref.state.pool.time)
    wall_base = time.perf_counter() - t0
    base_chain = ref.audit_chain()
    base_events = ref.counters()["events_committed"]

    exhaust_plan = [
        {"at": "1 s", "op": "exhaust_backend", "recover_after": 1}
    ]

    # (a) exhaust → ladder engages → completes with the exact chain
    t0 = time.perf_counter()
    sim = build()
    sim.attach_supervisor(quiet_supervisor())
    sim.attach_faults(plan_mod.parse_fault_plan(exhaust_plan))
    sim.run(windows_per_dispatch=4)
    jax.block_until_ready(sim.state.pool.time)
    wall_ladder = time.perf_counter() - t0
    pstats = sim.pressure_stats()
    ladder_engaged = (
        pstats.get("downshifts", 0) + pstats.get("spill_escalations", 0)
        >= 1
    )
    ladder_chain_equal = (
        sim.audit_chain() == base_chain
        and sim.counters()["events_committed"] == base_events
    )

    # (b) control arm — ladder disabled: the pre-ladder outcome, typed
    control = build()
    control.attach_pressure(
        PressureController(PressurePolicy(enabled=False))
    )
    control.attach_supervisor(quiet_supervisor(policy="abort"))
    control.attach_faults(plan_mod.parse_fault_plan(exhaust_plan))
    control_typed_abort = False
    try:
        control.run(windows_per_dispatch=4)
    except BackendLost:
        control_typed_abort = True

    # (c) saturate_pool → spill escalation absorbs it, chain identical
    sat = build()
    sat.attach_faults(plan_mod.parse_fault_plan(
        [{"at": "1 s", "op": "saturate_pool", "frac": 0.2}]
    ))
    sat.run(windows_per_dispatch=4)
    sat_chain_equal = (
        sat.audit_chain() == base_chain
        and sat.counters()["events_committed"] == base_events
    )
    sat_spilled = sat.spill_stats()["spill_episodes"] >= 1

    metrics_path = os.path.join(_REPO, "pressure_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(sim)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "pressure_smoke", "hosts": num_hosts,
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    pressure_recorded = (
        doc["counters"].get("pressure.ladder_steps", 0) >= 1
        and "pressure.estimated_bytes" in doc["gauges"]
    )

    return {
        "stage": "pressure_smoke",
        "platform": jax.default_backend(),
        "hosts": num_hosts,
        "chain": int(base_chain),
        "wall_base_s": round(wall_base, 3),
        "wall_ladder_s": round(wall_ladder, 3),
        "pressure": {k: int(v) for k, v in sorted(pstats.items())},
        "ladder_chain_equal": ladder_chain_equal,
        "control_typed_abort": control_typed_abort,
        "saturate_chain_equal": sat_chain_equal,
        "saturate_spill_episodes": int(
            sat.spill_stats()["spill_episodes"]
        ),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_ladder": bool(ladder_engaged and ladder_chain_equal),
        "gate_control": bool(control_typed_abort),
        "gate_saturate": bool(sat_chain_equal and sat_spilled),
        "gate": bool(
            ladder_engaged and ladder_chain_equal and control_typed_abort
            and sat_chain_equal and sat_spilled and pressure_recorded
        ),
    }


def _async_smoke_gml(shards: int, per: int, seed: int = 7) -> str:
    """The async-smoke topology: one vertex per host; DECOHERED
    intra-shard latencies (events stop clustering on a shared lattice, so
    the barrier can't batch different shards' windows together) with
    shard 0 drawn from a faster band (the DELIBERATE imbalance: it needs
    ~2x the windows of any other shard and serializes the barrier
    driver); cross-shard latencies large and distinct — the generous
    lookahead that lets every other shard run its own windows
    concurrently instead of idling through shard 0's."""
    import numpy as np

    rng = np.random.RandomState(seed)
    n = shards * per

    def band(a: int, b: int) -> tuple[int, int]:
        if a // per != b // per:
            return 700000, 900000  # cross-shard: the generous lookahead
        return (5000, 120000) if a // per == 0 else (60000, 250000)

    lines = ["graph ["]
    for v in range(n):
        lines.append(f"  node [ id {v} ]")
    for a in range(n):
        for b in range(a, n):
            lo, hi = band(a, b)
            lines.append(
                f'  edge [ source {a} target {b} latency '
                f'"{int(rng.randint(lo, hi))} us" ]'
            )
    lines.append("]")
    return "\n".join(lines)


def stage_async_smoke(shards: int = 4, hosts_per_shard: int = 4,
                      stop_s: int = 30, span: int = 2):
    """Async conservative-sync gate (ISSUE 10 acceptance): a deliberately
    imbalanced islands workload (locality-biased PHOLD on a decohered
    topology whose shard 0 runs a ~2x faster event timescale) driven by
    the barrier loop vs the per-shard-frontier async loop
    (parallel/islands.make_shard_run_to_async). Gates:

      * async wall < barrier wall, with the mechanism pinned by the
        superstep ratio (async needs strictly fewer device-loop
        iterations — the barrier serializes the union of all shards'
        windows, async overlaps them);
      * the global audit digest chain is BIT-IDENTICAL to the barrier
        run's (and committed events equal) — asynchrony changed the
        schedule, never the simulation;
      * the schema-v9 metrics artifact records async.* and validates
        under --strict-namespaces.

    CPU-deterministic by design (both arms run the same CPU backend), so
    no backend wait."""
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.sim import build_simulation

    gml = _async_smoke_gml(shards, hosts_per_shard)

    def cfg(async_on: bool) -> dict:
        hosts = {}
        for v in range(shards * hosts_per_shard):
            hosts[f"h{v:02d}"] = {
                "quantity": 1, "network_node_id": v, "app_model": "phold",
                "app_options": {
                    "msgload": 1, "runtime": stop_s - 1, "local_span": span,
                },
            }
        return {
            "general": {"stop_time": stop_s, "seed": 42},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "experimental": {
                "event_capacity": 2048, "events_per_host_per_window": 8,
                "outbox_slots": 8, "inbox_slots": 4,
                "num_shards": shards, "exchange_slots": 32,
                "async_islands": async_on,
            },
            "hosts": hosts,
        }

    def run_arm(async_on: bool):
        sim = build_simulation(cfg(async_on))
        # warm through compile + the aligned start burst, then time the
        # steady decohered region
        sim.run(until=2 * simtime.NS_PER_SEC, windows_per_dispatch=4096)
        jax.block_until_ready(sim.state.pool.time)
        t0 = time.perf_counter()
        sim.run(windows_per_dispatch=4096)
        jax.block_until_ready(sim.state.pool.time)
        return sim, time.perf_counter() - t0

    # interleave arms to decorrelate machine drift from the comparison
    barrier, w_b = run_arm(False)
    async_sim, w_a = run_arm(True)
    w_b = min(w_b, run_arm(False)[1])
    w_a = min(w_a, run_arm(True)[1])

    chain_equal = barrier.audit_chain() == async_sim.audit_chain()
    ev_b = barrier.counters()["events_committed"]
    ev_a = async_sim.counters()["events_committed"]
    steps_b, steps_a = barrier.windows_run, async_sim.windows_run
    astats = async_sim.async_stats() or {}

    metrics_path = os.path.join(_REPO, "async_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(async_sim)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "async_smoke", "hosts": shards * hosts_per_shard,
        "shards": shards,
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    async_recorded = (
        doc["counters"].get("async.supersteps", 0) > 0
        and "async.frontier_spread_max_ns" in doc["gauges"]
    )

    gate_wall = w_a < w_b
    gate_steps = steps_a < steps_b
    gate_chain = bool(chain_equal and ev_a == ev_b)
    return {
        "stage": "async_smoke",
        "platform": jax.default_backend(),
        "hosts": shards * hosts_per_shard,
        "shards": shards,
        "events": int(ev_a),
        "events_equal": ev_a == ev_b,
        "chain": int(async_sim.audit_chain()),
        "chain_equal": chain_equal,
        "supersteps_barrier": int(steps_b),
        "supersteps_async": int(steps_a),
        "superstep_ratio": round(steps_b / max(steps_a, 1), 2),
        "wall_barrier_s": round(w_b, 3),
        "wall_async_s": round(w_a, 3),
        "wall_ratio": round(w_b / w_a, 2) if w_a else 0.0,
        "async": {k: int(v) for k, v in sorted(astats.items())},
        "frontier_spread_max_ns": int(
            doc["gauges"].get("async.frontier_spread_max_ns", -1)
        ),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_wall": gate_wall,
        "gate_supersteps": gate_steps,
        "gate_chain": gate_chain,
        "gate": bool(
            gate_wall and gate_steps and gate_chain and async_recorded
        ),
    }


def stage_profile_smoke(shards: int = 4, hosts_per_shard: int = 4,
                        stop_s: int = 30, span: int = 2,
                        overhead_tol: float = 0.03):
    """shadowscope gate (ISSUE 20 acceptance): the profiling plane is
    observation, never participation. On the async-smoke workload (same
    topology/seed — shard 0 is the deliberately skewed hot shard):

      * profiler-on vs profiler-off runs keep BIT-IDENTICAL audit chains
        and equal committed events — the recorder is read-only against
        the sim;
      * profiler overhead <= 3% wall (min-of-2 per arm, interleaved);
      * critical-path attribution names shard 0 (the hot-frac shard the
        topology skews) from the recorded per-shard frontier intervals;
      * merging two runs' profile docs folds histograms EXACTLY (merged
        counts/sums equal the per-peer sums — the router /timez
        invariant);
      * the profile doc validates, and the schema-current metrics
        artifact carries prof.* keys under --strict-namespaces.

    Both arms run the same CPU backend — no backend wait."""
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.obs import prof as obs_prof
    from shadow_tpu.obs.hist import LogHistogram
    from shadow_tpu.sim import build_simulation

    gml = _async_smoke_gml(shards, hosts_per_shard)
    hosts = {}
    for v in range(shards * hosts_per_shard):
        hosts[f"h{v:02d}"] = {
            "quantity": 1, "network_node_id": v, "app_model": "phold",
            "app_options": {
                "msgload": 1, "runtime": stop_s - 1, "local_span": span,
            },
        }
    cfg = {
        "general": {"stop_time": stop_s, "seed": 42},
        "network": {"graph": {"type": "gml", "inline": gml}},
        "experimental": {
            "event_capacity": 2048, "events_per_host_per_window": 8,
            "outbox_slots": 8, "inbox_slots": 4,
            "num_shards": shards, "exchange_slots": 32,
            "async_islands": True,
        },
        "hosts": hosts,
    }

    def run_arm(profiled: bool):
        sim = build_simulation(cfg)
        prof = None
        if profiled:
            prof = obs_prof.ProfRecorder()
            sim.obs_session = obs_metrics.ObsSession(prof=prof)
        # small dispatches so every handoff boundary lands an interval
        # in the ring (the barrier-free loop still overlaps shards)
        sim.run(until=2 * simtime.NS_PER_SEC, windows_per_dispatch=64)
        jax.block_until_ready(sim.state.pool.time)
        t0 = time.perf_counter()
        sim.run(windows_per_dispatch=64)
        jax.block_until_ready(sim.state.pool.time)
        return sim, prof, time.perf_counter() - t0

    # interleave arms to decorrelate machine drift from the comparison
    off_sim, _, w_off = run_arm(False)
    on_sim, prof_a, w_on = run_arm(True)
    w_off = min(w_off, run_arm(False)[2])
    on2_sim, prof_b, w_on2 = run_arm(True)
    w_on = min(w_on, w_on2)

    chain_equal = off_sim.audit_chain() == on_sim.audit_chain()
    ev_off = off_sim.counters()["events_committed"]
    ev_on = on_sim.counters()["events_committed"]
    overhead = (w_on - w_off) / w_off if w_off > 0 else 0.0

    doc_a = prof_a.to_doc(meta={"peer": "a"})
    doc_b = prof_b.to_doc(meta={"peer": "b"})
    obs_prof.validate_profile_doc(doc_a)
    obs_prof.validate_profile_doc(doc_b)
    cp = obs_prof.critical_path(doc_a)

    # the federation /timez invariant: merged histograms ARE the sums
    merged = obs_prof.merge_profile_docs({"a": doc_a, "b": doc_b})
    merge_exact = True
    for name in set(doc_a["hists"]) | set(doc_b["hists"]):
        ha = LogHistogram.from_doc(
            doc_a["hists"][name]) if name in doc_a["hists"] \
            else LogHistogram()
        hb = LogHistogram.from_doc(
            doc_b["hists"][name]) if name in doc_b["hists"] \
            else LogHistogram()
        hm = LogHistogram.from_doc(merged["hists"][name])
        if hm.count != ha.count + hb.count \
                or hm.sum != ha.sum + hb.sum:
            merge_exact = False

    gate_chain = bool(chain_equal and ev_on == ev_off)
    gate_overhead = overhead <= overhead_tol
    gate_critical = cp is not None and cp["critical_shard"] == 0
    gate_recorded = prof_a.recorded > 0 and bool(doc_a["hists"])

    gate = bool(
        gate_chain and gate_overhead and gate_critical
        and gate_recorded and merge_exact
    )
    metrics_path = os.path.join(_REPO, "profile_smoke.metrics.json")
    session = on_sim.obs_session  # carries the run's spans + prof_a
    session.finalize(on_sim)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "profile_smoke", "hosts": shards * hosts_per_shard,
        "shards": shards, "wall_s": round(w_on, 3), "ok": gate,
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    prof_recorded = (
        doc["counters"].get("prof.intervals", 0) > 0
        and "prof.critical_shard" in doc["gauges"]
    )
    return {
        "stage": "profile_smoke",
        "platform": jax.default_backend(),
        "hosts": shards * hosts_per_shard,
        "shards": shards,
        "events": int(ev_on),
        "chain_equal": chain_equal,
        "wall_off_s": round(w_off, 3),
        "wall_on_s": round(w_on, 3),
        "overhead_frac": round(overhead, 4),
        "intervals": int(prof_a.recorded),
        "dropped": int(prof_a.dropped),
        "critical_shard": None if cp is None else cp["critical_shard"],
        "critical_wall_frac": None if cp is None
        else round(cp["wall_frac"], 3),
        "blocked_frac": None if cp is None
        else round(cp["blocked_frac"], 3),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_chain": gate_chain,
        "gate_overhead": gate_overhead,
        "gate_critical": gate_critical,
        "gate_merge": merge_exact,
        "gate_recorded": bool(gate_recorded and prof_recorded),
        "gate": bool(gate and prof_recorded),
    }


def _balance_smoke_gml(shards: int, per: int, seed: int = 7) -> str:
    """The balance-smoke topology: one vertex per host, decohered
    UNIFORM intra-shard latency bands (no structurally fast shard — the
    hotness must come from the `skew_hosts` injection, not the graph)
    and large distinct cross-shard latencies (generous lookahead, so the
    only thing that throttles the healthy shards is a laggard's
    frontier)."""
    import numpy as np

    rng = np.random.RandomState(seed)
    n = shards * per

    def band(a: int, b: int) -> tuple[int, int]:
        if a // per != b // per:
            return 700000, 900000
        return 30000, 250000

    lines = ["graph ["]
    for v in range(n):
        lines.append(f"  node [ id {v} ]")
    for a in range(n):
        for b in range(a, n):
            lo, hi = band(a, b)
            lines.append(
                f'  edge [ source {a} target {b} latency '
                f'"{int(rng.randint(lo, hi))} us" ]'
            )
    lines.append("]")
    return "\n".join(lines)


def stage_balance_smoke(shards: int = 4, per: int = 4, stop_s: int = 10,
                        skew_at_s: int = 2, settle_s: int = 4):
    """Self-balancing fleet gate (ISSUE 11 acceptance): a hot-shard
    workload DRIVEN by a `skew_hosts` injection — destination-biased
    PHOLD (half of all traffic targets shard 0's hosts) whose pending
    events are replicated 6x at t=2s — run three ways:

      control   balancer off: shard 0 stays the chronic frontier
                laggard for the rest of the run;
      balanced  balancer on: the hot shard is detected (occupancy +
                laggard hysteresis), the assignment refined by min-cut,
                and hosts migrated live through the traced-lookahead
                seam;
      rollback  balancer on with a FORCED mid-migration failure on the
                first attempt (ShardBalancer.inject_failure_next): the
                move must roll back to the pre-move layout + cooldown.

    Gates: the balanced arm shows LOWER post-settle frontier spread and
    FEWER blocked_on_neighbor supersteps than control; all three arms'
    audit digest chains are BIT-IDENTICAL (migrations and rollbacks
    change the schedule, never the simulation); at least one migration
    committed and the rollback arm rolled back; the balanced run is
    retrace-free (migrations never recompile — hlo_audit.retrace_report
    gate); and the schema-v10 metrics artifact records balance.* and
    validates under --strict-namespaces. CPU-deterministic by design."""
    import jax

    from shadow_tpu.analysis import hlo_audit
    from shadow_tpu.core import simtime
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.sim import build_simulation

    gml = _balance_smoke_gml(shards, per)
    n = shards * per

    def cfg(balancer: bool) -> dict:
        hosts = {}
        for v in range(n):
            hosts[f"h{v:02d}"] = {
                "quantity": 1, "network_node_id": v, "app_model": "phold",
                "app_options": {
                    "msgload": 2, "runtime": stop_s - 1,
                    # persistent destination bias: half of ALL forwards
                    # target shard 0's hosts, so the skew_hosts
                    # amplification keeps re-concentrating there until
                    # (unless) the balancer spreads those hosts out
                    "hot_frac": per / n, "hot_share": 0.5,
                },
            }
        return {
            "general": {"stop_time": stop_s, "seed": 42},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "experimental": {
                "event_capacity": 4096, "events_per_host_per_window": 8,
                "outbox_slots": 8, "inbox_slots": 4,
                "num_shards": shards, "exchange_slots": 32,
                "rebalance": True,  # control arm compiles the same
                # slot_of-routing kernel, so the comparison is balancer
                # policy only, never kernel shape
                "balancer": balancer,
                "balance_streak": 3, "balance_cooldown": 8,
                "balance_hot_ratio": 1.5,
            },
            "hosts": hosts,
            "faults": {"inject": [{
                "at": f"{skew_at_s} s", "op": "skew_hosts",
                "span": [0, per], "factor": 6,
            }]},
        }

    settle_ns = (skew_at_s + settle_s) * simtime.NS_PER_SEC

    def run_arm(mode: str):
        sim = build_simulation(cfg(mode != "control"))
        sim.attach_faults(sim.config.faults.load_faults())
        if mode == "rollback":
            sim.balancer.inject_failure_next()
        # phase 1: pre-skew + skew + the balancer's detection/migration
        # window; phase 2 (post-settle) is what the gates measure
        sim.run(until=settle_ns, windows_per_dispatch=16)
        blocked0 = (sim.async_stats() or {}).get("blocked_on_neighbor", 0)
        sim.reset_frontier_spread()
        sim.run(windows_per_dispatch=16)
        blocked2 = (
            (sim.async_stats() or {}).get("blocked_on_neighbor", 0)
            - blocked0
        )
        spread2 = (sim.async_gauges() or {}).get(
            "frontier_spread_max_ns", -1
        )
        return sim, blocked2, spread2

    control, blocked_c, spread_c = run_arm("control")
    balanced, blocked_b, spread_b = run_arm("balanced")
    rollback, blocked_r, _ = run_arm("rollback")

    chain = balanced.audit_chain()
    chains_equal = (
        chain == control.audit_chain() == rollback.audit_chain()
    )
    ev = balanced.counters()["events_committed"]
    events_equal = (
        ev == control.counters()["events_committed"]
        == rollback.counters()["events_committed"]
    )
    bstats = balanced.balance_stats() or {}
    rstats = rollback.balance_stats() or {}
    retrace = hlo_audit.retrace_report(balanced)

    metrics_path = os.path.join(_REPO, "balance_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(balanced)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "balance_smoke", "hosts": n, "shards": shards,
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    balance_recorded = (
        doc["counters"].get("balance.migrations", 0) > 0
        and "balance.state" in doc["gauges"]
    )

    gate_blocked = blocked_b < blocked_c
    gate_spread = 0 <= spread_b < spread_c
    gate_chain = bool(chains_equal and events_equal)
    gate_heal = bstats.get("migrations", 0) >= 1
    gate_rollback = rstats.get("rollbacks", 0) >= 1
    return {
        "stage": "balance_smoke",
        "platform": jax.default_backend(),
        "hosts": n,
        "shards": shards,
        "events": int(ev),
        "chain": int(chain),
        "chain_equal": bool(chains_equal),
        "events_equal": bool(events_equal),
        "skewed_rows": int(
            balanced.fault_stats().get("events_skewed", 0)
        ),
        "migrations": int(bstats.get("migrations", 0)),
        "hosts_moved": int(bstats.get("hosts_moved", 0)),
        "rollbacks_in_rollback_arm": int(rstats.get("rollbacks", 0)),
        "blocked_control": int(blocked_c),
        "blocked_balanced": int(blocked_b),
        "blocked_rollback_arm": int(blocked_r),
        "spread_control_ns": int(spread_c),
        "spread_balanced_ns": int(spread_b),
        "shard_loads_control": [int(x) for x in control.shard_loads()],
        "shard_loads_balanced": [int(x) for x in balanced.shard_loads()],
        "retrace_ok": bool(retrace["ok"]),
        "kernel_compiles": int(retrace["compiles_total"]),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_blocked": bool(gate_blocked),
        "gate_spread": bool(gate_spread),
        "gate_chain": gate_chain,
        "gate_heal": bool(gate_heal),
        "gate_rollback": bool(gate_rollback),
        "gate": bool(
            gate_blocked and gate_spread and gate_chain and gate_heal
            and gate_rollback and retrace["ok"] and balance_recorded
        ),
    }


def _mesh_smoke_gml(hosts: int, comm: int, offset: int, span: int,
                    seed: int = 7) -> str:
    """The mesh-smoke topology: one vertex per host on a ring,
    DIRECT-EDGE routing only (use_shortest_path false, so the in-edge
    matrix is genuinely sparse — shortest-path baking would make every
    shard pair adjacent and reduce ppermute to a ring all_gather).
    Hosts within ring distance <= span are connected; COMMUNITIES of
    `comm` contiguous hosts, offset by `offset` from the chip-block
    boundaries, get fast decohered intra links (the chatty pairs) while
    community-crossing links are ~15x slower. The block partition
    therefore splits every community across two chips — its min cross-
    chip lookahead is the FAST band, so neighbor blocking is chronic —
    while the min-cut placement re-aligns chips onto communities and
    only the slow boundary links cross."""
    import numpy as np

    rng = np.random.RandomState(seed)
    lines = ["graph ["]
    for v in range(hosts):
        lines.append(f"  node [ id {v} ]")
    for a in range(hosts):
        lines.append(
            f'  edge [ source {a} target {a} latency '
            f'"{int(rng.randint(2000, 3000))} us" ]'
        )
        for d in range(1, span + 1):
            b = (a + d) % hosts
            same = ((a - offset) % hosts) // comm == (
                (b - offset) % hosts) // comm
            lo, hi = (3000, 6000) if same else (45000, 60000)
            lines.append(
                f'  edge [ source {a} target {b} latency '
                f'"{int(rng.randint(lo, hi))} us" ]'
            )
    lines.append("]")
    return "\n".join(lines)


def stage_mesh_smoke(shards: int = 8, per: int = 4, stop_s: int = 8,
                     span: int = 3):
    """True multi-chip gate (ISSUE 12 acceptance): the fused async
    islands driver runs as `shard_map` over an 8-chip virtual CPU mesh
    with per-chip state placement and NEIGHBOR-ONLY ppermute frontier
    exchange, against two references:

      vmap      the single-program islands run (one chip, virtual
                shards) — the bit-identity reference;
      gather    shard_map with the all_gather frontier exchange and the
                block placement — the collective-volume/blocking
                comparison arm.

    Gates: all three audit digest chains BIT-IDENTICAL (mesh execution
    changes where state lives, never the simulation); ZERO all-gather
    ops in the optimized HLO of the mesh kernel's frontier exchange
    (hlo_audit.all_gather_lines; the control arm shows >0); cross-chip
    collective volume scales with in-edge degree — the ppermute arm's
    analytic frontier-exchange bytes AND blocked-on-neighbor supersteps
    both land strictly below the gather arm's (min-cut placement keeps
    the fast community links intra-chip, so horizons are bounded by the
    slow boundary links only); and the mesh arm is RETRACE-FREE across
    a mid-run gear shift and a live host migration (retrace_report ok,
    zero exchange-schedule rebuilds). Writes the schema-v11 mesh.*
    metrics artifact, strict-namespace-validated. CPU-deterministic by
    design (all arms share one backend), so no backend wait."""
    import numpy as np

    import jax

    from shadow_tpu.analysis import hlo_audit
    from shadow_tpu.core import simtime
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.sim import build_simulation

    n = shards * per
    comm = per  # community size = chip size, offset so blocks split them
    offset = per // 2
    gml = _mesh_smoke_gml(n, comm, offset, span)

    def cfg(mode: str, exchange: str, placement: str,
            pool_gears: int = 1) -> dict:
        hosts = {}
        for v in range(n):
            hosts[f"h{v:02d}"] = {
                "quantity": 1, "network_node_id": v, "app_model": "phold",
                "app_options": {
                    "msgload": 1, "runtime": stop_s - 1, "local_span": span,
                },
            }
        return {
            "general": {"stop_time": stop_s, "seed": 42},
            "network": {
                "graph": {"type": "gml", "inline": gml},
                "use_shortest_path": False,
            },
            "experimental": {
                "event_capacity": 4096, "events_per_host_per_window": 8,
                "outbox_slots": 8, "inbox_slots": 4,
                "num_shards": shards, "exchange_slots": 16,
                "island_mode": mode, "mesh_exchange": exchange,
                "placement": placement, "pool_gears": pool_gears,
                "rebalance": True,
            },
            "hosts": hosts,
        }

    def boundary_swap(sim) -> None:
        """One live migration that PRESERVES shard-level connectivity:
        swap a boundary host pair between chips 0 and 1 chosen so every
        in-edge of the swapped layout still rides the compiled ppermute
        schedule (exchange_rebuilds must stay 0) — exactly the kind of
        move the balancer's cut-aware refinement prefers. Deterministic:
        first covered (a, b) pair in slot order."""
        from shadow_tpu.parallel import lookahead as lookahead_mod

        slot0 = np.asarray(jax.device_get(sim.params.slot_of))
        Hl = n // shards
        host_at = np.empty(n, np.int64)
        host_at[slot0] = np.arange(n)
        for sa in range(Hl):
            for sb in range(Hl, 2 * Hl):
                a, b = int(host_at[sa]), int(host_at[sb])
                slot = slot0.copy()
                slot[a], slot[b] = slot[b], slot[a]
                spec = lookahead_mod.derive(
                    sim._latency_np, sim._host_vertex_g, shards,
                    assignment=slot,
                )
                if lookahead_mod.shifts_covered(
                    spec, sim._async_shifts
                ):
                    sim.migrate_hosts(slot)
                    return
        raise RuntimeError(
            "mesh smoke: no connectivity-preserving boundary swap exists"
        )

    t0 = time.perf_counter()
    ref = build_simulation(cfg("vmap", "ppermute", "block"))
    ref.run(windows_per_dispatch=512)
    chain_ref = ref.audit_chain()
    ev_ref = ref.counters()["events_committed"]

    gather = build_simulation(cfg("shard_map", "all_gather", "block"))
    gather.run(windows_per_dispatch=512)

    mesh = build_simulation(
        cfg("shard_map", "ppermute", "min_cut", pool_gears=2)
    )
    # first leg, then a forced gear round-trip + a live migration at the
    # dispatch boundary — the retrace-freedom chaos the gate requires
    mesh.run(until=2 * simtime.NS_PER_SEC, windows_per_dispatch=512)
    top = mesh._gear_ladder[-1].level
    if top > 0:  # forced round-trip: both tiers' kernels run this smoke
        other = top - 1 if mesh._gear == top else top
        here = mesh._gear
        mesh._shift_gear(other)
        mesh._shift_gear(here)
    boundary_swap(mesh)
    mesh.run(windows_per_dispatch=512)

    chain_equal = (
        mesh.audit_chain() == chain_ref
        and gather.audit_chain() == chain_ref
    )
    ev_mesh = mesh.counters()["events_committed"]
    ev_gather = gather.counters()["events_committed"]

    # HLO gate: the mesh kernel's frontier exchange compiles to
    # collective-permutes only; the gather arm is the positive control
    def async_hlo(sim):
        fn = sim._gear_fns[sim._gear]["run_to_async"]
        return fn.lower(
            sim.state, sim.params, sim._async_runahead,
            sim._async_look_in, sim._async_spread,
            hlo_audit.DEFAULT_WIN_END, 8,
        ).compile().as_text()

    mesh_ag = len(hlo_audit.all_gather_lines(async_hlo(mesh)))
    control_ag = len(hlo_audit.all_gather_lines(async_hlo(gather)))
    retrace = hlo_audit.retrace_report(mesh)

    mstats = mesh.mesh_stats() or {}
    gstats = gather.mesh_stats() or {}
    bytes_mesh = mstats.get("frontier_exchange_bytes", 0)
    bytes_gather = gstats.get("frontier_exchange_bytes", 0)
    blocked_mesh = (mesh.async_stats() or {}).get("blocked_on_neighbor", 0)
    blocked_gather = (gather.async_stats() or {}).get(
        "blocked_on_neighbor", 0)

    metrics_path = os.path.join(_REPO, "mesh_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(mesh)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "mesh_smoke", "hosts": n, "chips": shards,
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    mesh_recorded = (
        doc["counters"].get("mesh.frontier_exchange_bytes", 0) > 0
        and doc["gauges"].get("mesh.shard_map") == 1
        and "mesh.events_per_chip_max" in doc["gauges"]
    )

    gate_chain = bool(
        chain_equal and ev_mesh == ev_ref and ev_gather == ev_ref
    )
    gate_no_all_gather = mesh_ag == 0 and control_ag > 0
    gate_volume = bytes_mesh < bytes_gather
    gate_blocked = blocked_mesh < blocked_gather
    gate_retrace = bool(
        retrace["ok"] and mstats.get("exchange_rebuilds", 0) == 0
    )
    return {
        "stage": "mesh_smoke",
        "platform": jax.default_backend(),
        "devices": len(jax.devices()),
        "hosts": n,
        "chips": shards,
        "events": int(ev_mesh),
        "chain": int(mesh.audit_chain()),
        "chain_equal": chain_equal,
        "exchange_partners": int(mesh.exchange_partners),
        "in_degree_max": int(
            doc["gauges"].get("mesh.in_degree_max", -1)),
        "all_gathers_mesh": int(mesh_ag),
        "all_gathers_control": int(control_ag),
        "frontier_bytes_mesh": int(bytes_mesh),
        "frontier_bytes_gather": int(bytes_gather),
        "volume_ratio": round(bytes_gather / max(bytes_mesh, 1), 2),
        "blocked_mesh": int(blocked_mesh),
        "blocked_gather": int(blocked_gather),
        "migrations": int(mesh.rebalances),
        "gear_shifts": int(mesh._gear_shifts),
        "exchange_rebuilds": int(mstats.get("exchange_rebuilds", -1)),
        "cut_cost": doc["gauges"].get("mesh.cut_cost"),
        "cut_cost_block": doc["gauges"].get("mesh.cut_cost_block"),
        "kernel_compiles": int(retrace["compiles_total"]),
        "retraced": {k: int(v) for k, v in retrace["retraced"].items()},
        "wall_s": round(time.perf_counter() - t0, 3),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_chain": gate_chain,
        "gate_no_all_gather": bool(gate_no_all_gather),
        "gate_volume": bool(gate_volume),
        "gate_blocked": bool(gate_blocked),
        "gate_retrace": gate_retrace,
        "gate": bool(
            gate_chain and gate_no_all_gather and gate_volume
            and gate_blocked and gate_retrace and mesh_recorded
        ),
    }


def stage_mesh_resilience_smoke(shards: int = 8, per: int = 7,
                                stop_s: int = 6, span: int = 3):
    """Elastic mesh resilience gate (ISSUE 13 acceptance): a kill_chip
    mid-run on an 8-chip virtual CPU mesh drains to a checkpoint,
    relayouts onto the 7 surviving chips, CONTINUES, and — once the
    chip answers probes again — re-expands back to 8 at a dispatch
    boundary (parallel/elastic.py). Four arms:

      control   the uninterrupted 8-chip shard_map run — the chain
                reference;
      elastic   kill_chip {at 2s, chip 3, recovers} under policy
                `relayout`: drain → 7-chip relayout → re-expand → finish;
      wait      the same kill_chip under policy `wait` (hot resume on
                the full mesh once the chip answers) — the control arm
                proving relayout adds nothing the chain can see;
      shrink1   a 2-chip mesh losing one chip falls back to the GLOBAL
                engine (islands.globalize_state), the S→1 endpoint.

    Gates: every arm's audit chain and committed-event total BIT-
    IDENTICAL to its uninterrupted reference; exactly one counted
    kernel rebuild per mesh change (relayouts + re_expansions ==
    kernel_rebuilds − 1, and the re-expanded sim is retrace-free);
    ZERO all-gathers in the final mesh kernel's optimized HLO (the PR 12
    pin, unchanged by the elastic plane); drain checkpoints live in the
    drain-* namespace with the periodic ring intact; and the schema-v12
    mesh.* artifact strict-validates with the relayout counters
    recorded. CPU-deterministic (the injection is the outage, probes
    are countdown-driven), so no backend wait."""
    import tempfile

    import numpy as np  # noqa: F401 — config helpers below use jax only

    import jax

    from shadow_tpu.analysis import hlo_audit
    from shadow_tpu.core import checkpoint as ckpt_mod
    from shadow_tpu.core.supervisor import BackendSupervisor
    from shadow_tpu.faults import plan as plan_mod
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.parallel import elastic as elastic_mod
    from shadow_tpu.sim import build_simulation

    n = shards * per
    comm = per
    offset = per // 2
    gml = _mesh_smoke_gml(n, comm, offset, span)

    def cfg(hosts_n: int, chips: int, graph: str, stop: int) -> dict:
        hosts = {}
        for v in range(hosts_n):
            hosts[f"h{v:02d}"] = {
                "quantity": 1, "network_node_id": v, "app_model": "phold",
                "app_options": {
                    "msgload": 1, "runtime": stop - 1, "local_span": span,
                },
            }
        return {
            "general": {"stop_time": stop, "seed": 42},
            "network": {
                "graph": {"type": "gml", "inline": graph},
                "use_shortest_path": False,
            },
            "experimental": {
                "event_capacity": 8192, "events_per_host_per_window": 8,
                "outbox_slots": 8, "inbox_slots": 4,
                "num_shards": chips, "exchange_slots": 16,
                "island_mode": "shard_map",
            },
            "hosts": hosts,
        }

    def quiet_sup(policy):
        return BackendSupervisor(policy, sleep=lambda s: None,
                                 probe_budget_s=60.0)

    kill = [{"at": "2 s", "op": "kill_chip", "chip": 3,
             "recover_after": 2}]

    t0 = time.perf_counter()
    # --- control: uninterrupted 8-chip mesh ---
    base = cfg(n, shards, gml, stop_s)
    control = build_simulation(base)
    control.run(windows_per_dispatch=64)
    chain_ref = control.audit_chain()
    ev_ref = control.counters()["events_committed"]

    # --- elastic arm: kill → drain → relayout(7) → re-expand(8) ---
    with tempfile.TemporaryDirectory(prefix="mesh_resilience_") as td:
        runner = elastic_mod.ElasticMeshRunner(
            elastic_mod.config_builder(base), chips=shards, ckpt_dir=td,
            supervisor=quiet_sup("relayout"),
            faults=plan_mod.parse_fault_plan(kill),
            probe_every=1, hysteresis=2, cooldown=1,
            windows_per_dispatch=32,
        )
        mesh = runner.run()
        chain_elastic = mesh.audit_chain()
        ev_elastic = mesh.counters()["events_committed"]
        rstats = runner.stats()
        # drain-namespace satellite: the drains never touched the
        # periodic ring's namespace
        drains = ckpt_mod.ring_entries(td, prefix="drain")
        gate_drain_ns = len(drains) >= 2  # chip loss + re-expand

        # metrics artifact (schema v12, strict namespaces)
        metrics_path = os.path.join(
            _REPO, "mesh_resilience_smoke.metrics.json"
        )
        session = obs_metrics.ObsSession()
        session.finalize(mesh)
        doc = session.metrics.dump(metrics_path, meta={
            "stage": "mesh_resilience_smoke", "hosts": n, "chips": shards,
        })
        obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
        v12_recorded = (
            doc["schema_version"] == obs_metrics.SCHEMA_VERSION
            and doc["counters"].get("mesh.relayouts") == 1
            and doc["counters"].get("mesh.re_expansions") == 1
            and doc["counters"].get("mesh.chips_lost") == 1
            and doc["counters"].get("resilience.chip_losses", 0) >= 1
            and doc["gauges"].get("mesh.chips_up") == shards
            and doc["gauges"].get("mesh.chips_total") == shards
        )

        # the PR 12 hlo pin, unchanged: the re-expanded mesh kernel's
        # frontier exchange still lowers to collective-permutes only
        fn = mesh._gear_fns[mesh._gear]["run_to_async"]
        hlo = fn.lower(
            mesh.state, mesh.params, mesh._async_runahead,
            mesh._async_look_in, mesh._async_spread,
            hlo_audit.DEFAULT_WIN_END, 8,
        ).compile().as_text()
        mesh_ag = len(hlo_audit.all_gather_lines(hlo))
        retrace = hlo_audit.retrace_report(mesh)

    # --- wait-policy control arm: hot resume on the full mesh ---
    waits = build_simulation(base)
    waits.attach_supervisor(quiet_sup("wait"))
    waits.attach_faults(plan_mod.parse_fault_plan(kill))
    waits.run(windows_per_dispatch=32)
    chain_wait = waits.audit_chain()
    ev_wait = waits.counters()["events_committed"]

    # --- shrink-to-1 arm: 2 chips → 1 falls back to the global engine ---
    n1 = 2 * per
    gml1 = _mesh_smoke_gml(n1, per, per // 2, span)
    base1 = cfg(n1, 2, gml1, stop_s - 2)
    ref1 = build_simulation(base1)
    ref1.run(windows_per_dispatch=64)
    with tempfile.TemporaryDirectory(prefix="mesh_shrink1_") as td:
        runner1 = elastic_mod.ElasticMeshRunner(
            elastic_mod.config_builder(base1), chips=2, ckpt_dir=td,
            supervisor=quiet_sup("relayout"),
            faults=plan_mod.parse_fault_plan(
                [{"at": "1 s", "op": "kill_chip", "chip": 1}]
            ),
            windows_per_dispatch=32,
        )
        shrunk = runner1.run()
        gate_shrink1 = (
            shrunk.audit_chain() == ref1.audit_chain()
            and shrunk.counters()["events_committed"]
            == ref1.counters()["events_committed"]
            and not hasattr(shrunk, "num_shards")  # the global engine
        )

    gate_chain = bool(
        chain_elastic == chain_ref and ev_elastic == ev_ref
        and chain_wait == chain_ref and ev_wait == ev_ref
    )
    gate_elastic = (
        rstats["relayouts"] == 1 and rstats["re_expansions"] == 1
    )
    # one counted kernel rebuild per mesh change: the initial build plus
    # exactly one per relayout/re-expansion, and the final sim retraces
    # nothing on top of its own build
    gate_rebuilds = (
        rstats["kernel_rebuilds"] - 1
        == rstats["relayouts"] + rstats["re_expansions"]
        and retrace["ok"]
    )
    gate_hlo = mesh_ag == 0
    return {
        "stage": "mesh_resilience_smoke",
        "platform": jax.default_backend(),
        "devices": len(jax.devices()),
        "hosts": n,
        "chips": shards,
        "events": int(ev_elastic),
        "chain": int(chain_elastic),
        "relayouts": int(rstats["relayouts"]),
        "re_expansions": int(rstats["re_expansions"]),
        "chips_lost": int(rstats["chips_lost"]),
        "kernel_rebuilds": int(rstats["kernel_rebuilds"]),
        "relayout_downtime_ms": round(
            rstats["relayout_downtime_ns"] / 1e6, 1
        ),
        "drain_checkpoints": len(drains),
        "all_gathers_mesh": int(mesh_ag),
        "wall_s": round(time.perf_counter() - t0, 3),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_chain": gate_chain,
        "gate_elastic": bool(gate_elastic),
        "gate_rebuilds": bool(gate_rebuilds),
        "gate_hlo": bool(gate_hlo),
        "gate_shrink1": bool(gate_shrink1),
        "gate_drain_namespace": bool(gate_drain_ns),
        "gate_v12": bool(v12_recorded),
        "gate": bool(
            gate_chain and gate_elastic and gate_rebuilds and gate_hlo
            and gate_shrink1 and gate_drain_ns and v12_recorded
        ),
    }


_SERVE_SMOKE_SWEEP = {
    "sweep": {
        "name": "serve-smoke",
        "lanes": 2,
        "matrix": {
            "general.seed": [11, 12, 13, 14],
            "general.stop_time": ["900 ms", "1.4 s"],
        },
    },
    "fleet": {"windows_per_dispatch": 2},
}


def stage_serve_smoke(num_hosts: int = 64, msgload: int = 2):
    """Sim-as-a-service gate (ISSUE 8 acceptance): submit a sweep to the
    daemon, SIGKILL it mid-sweep, restart it with the same state dir,
    and require (a) the journal-replayed sweep to finish with per-job
    audit digest chains bit-identical (and identically ordered) to an
    uninterrupted in-process fleet run, and (b) the restarted daemon to
    perform ZERO window-kernel Python traces — every fleet shape binds
    from the AOT cache the first incarnation exported. Writes the
    daemon's schema-v7 serve.* metrics document as the stage artifact.
    CPU-deterministic (the kill is wall-clock-timed but the chains are
    virtual-time functions, so WHERE it lands never changes the bar)."""
    import tempfile

    from shadow_tpu.fleet import build_fleet, load_sweep
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.serve.client import ServeClient, ServeClientError

    doc = {
        **_fleet_smoke_job(seed=1, stop_s=1.0, num_hosts=num_hosts,
                           msgload=msgload),
        **{k: json.loads(json.dumps(v))
           for k, v in _SERVE_SMOKE_SWEEP.items()},
    }

    with tempfile.TemporaryDirectory(prefix="serve_smoke_") as td:
        state_dir = os.path.join(td, "state")
        cache_dir = os.path.join(td, "cache")  # fresh: cold → warm is real
        sock = os.path.join(state_dir, "serve.sock")
        env = {**os.environ, "SHADOW_TPU_CACHE_DIR": cache_dir}

        def start():
            proc = subprocess.Popen(
                [sys.executable, "-m", "shadow_tpu", "serve",
                 "--state-dir", state_dir,
                 "--checkpoint-every-dispatches", "1"],
                env=env, cwd=_REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            client = ServeClient(sock, timeout=30)
            client.wait_ready(timeout_s=120)
            return proc, client

        t0 = time.perf_counter()
        proc, client = start()
        sid = client.submit(doc)["id"]
        killed_at = None
        while True:
            info = client.sweep(sid)
            progress = info.get("progress") or {}
            if info["status"] in ("done", "failed"):
                break  # too fast to kill mid-run; gate still meaningful
            if progress.get("jobs_done", 0) >= 2:
                killed_at = dict(progress)
                break
            time.sleep(0.1)
        proc.kill()
        proc.wait()

        proc, client = start()
        info = client.wait(sid, timeout_s=600)
        stats = info["stats"] or {}
        metrics_doc = client.metrics()
        try:
            client.drain()
        except ServeClientError:
            pass
        proc.wait(timeout=60)
        wall = time.perf_counter() - t0

    metrics_path = os.path.join(_REPO, "serve_smoke.metrics.json")
    with open(metrics_path, "w") as f:
        json.dump(metrics_doc, f, indent=1)
        f.write("\n")
    obs_metrics.validate_metrics_doc(metrics_doc)

    # uninterrupted reference: the same sweep as one in-process fleet
    jobs, _ = load_sweep(json.loads(json.dumps(doc)))
    ref = build_fleet(jobs, lanes=2, windows_per_dispatch=2)
    ref.run()
    ref_rows = ref.results()
    rows = info.get("results") or []
    chains_equal = (
        [r["name"] for r in rows] == [r["name"] for r in ref_rows]
        and [r.get("audit", {}).get("chain") for r in rows]
        == [r["audit"]["chain"] for r in ref_rows]
    )
    zero_recompiles = stats.get("kernel_traces", -1) == 0
    serve_counters = {
        k: v for k, v in metrics_doc["counters"].items()
        if k.startswith("serve.")
    }
    return {
        "stage": "serve_smoke",
        "hosts": num_hosts,
        "jobs": len(ref_rows),
        "killed_at": killed_at,
        "status": info["status"],
        "wall_s": round(wall, 3),
        "chains_equal": chains_equal,
        "restart_kernel_traces": stats.get("kernel_traces"),
        "serve": serve_counters,
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_chains": bool(chains_equal and info["status"] == "done"),
        "gate_zero_recompiles": bool(zero_recompiles),
        "gate": bool(
            chains_equal and info["status"] == "done" and zero_recompiles
            and killed_at is not None
        ),
    }


def stage_federation_smoke(num_hosts: int = 64, msgload: int = 2):
    """Federated serve plane gate (ISSUE 18 acceptance): 3 daemons +
    the router, all sharing one kcache root. Choreography:

      1. warm one sweep through the router (pays the only traces);
      2. submit a mixed-tenant batch with a same-tenant burst — sticky
         affinity piles it onto one peer, so idle peers STEAL through
         the journaled handoff path (`federation.steals >= 1`);
      3. SIGKILL the loaded peer mid-sweep — the router's probe ladder
         declares it lost, replays its journal, and re-places every
         unfinished sweep onto the survivors.

    Gates: every batch sweep settles `done` with per-job audit chains
    bit-identical to an uninterrupted in-process fleet run of the same
    document; at least one steal and one failover-replayed sweep; ZERO
    window-kernel traces on every batch sweep (the shared AOT cache
    means peers that never saw the shape bind warm); and the router's
    schema-v16 `federation.*` metrics document STRICT-validates as the
    stage artifact. CPU-deterministic: the kill is wall-clock-timed but
    chains are virtual-time functions, so where it lands never changes
    the bar."""
    import tempfile

    from shadow_tpu.fleet import build_fleet, load_sweep
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.serve.client import ServeClient, ServeClientError

    def sweep_doc(name: str, seed: int) -> dict:
        return {
            **_fleet_smoke_job(seed=seed, stop_s=1.0, num_hosts=num_hosts,
                               msgload=msgload),
            "sweep": {
                "name": name,
                "lanes": 2,
                "matrix": {"general.seed": [seed, seed + 1]},
            },
            "fleet": {"windows_per_dispatch": 2},
        }

    batch = [
        # the same-tenant burst (affinity pile-up -> steal pressure) ...
        ("team-a", sweep_doc("fed-a0", 21)),
        ("team-a", sweep_doc("fed-a1", 31)),
        ("team-a", sweep_doc("fed-a2", 41)),
        ("team-a", sweep_doc("fed-a3", 51)),
        # ... plus a second tenant so placement is mixed, not monoculture
        ("team-b", sweep_doc("fed-b0", 61)),
        ("team-b", sweep_doc("fed-b1", 71)),
    ]

    with tempfile.TemporaryDirectory(prefix="federation_smoke_") as td:
        cache_dir = os.path.join(td, "cache")  # ONE root, all peers
        env = {**os.environ, "SHADOW_TPU_CACHE_DIR": cache_dir}
        peers = {f"p{i}": os.path.join(td, f"p{i}") for i in range(3)}
        router_dir = os.path.join(td, "router")

        def start_peer(name: str):
            return subprocess.Popen(
                [sys.executable, "-m", "shadow_tpu", "serve",
                 "--state-dir", peers[name],
                 "--checkpoint-every-dispatches", "1"],
                env=env, cwd=_REPO,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )

        t0 = time.perf_counter()
        procs = {name: start_peer(name) for name in peers}
        for name in peers:
            ServeClient(
                os.path.join(peers[name], "serve.sock"), timeout=30
            ).wait_ready(timeout_s=120)
        router_proc = subprocess.Popen(
            [sys.executable, "-m", "shadow_tpu", "route",
             "--state-dir", router_dir,
             "--probe-interval", "0.25", "--lost-after", "3",
             "--peers"] + [f"{n}={d}" for n, d in peers.items()],
            env=env, cwd=_REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        router = ServeClient(
            os.path.join(router_dir, "route.sock"), timeout=30, retries=3
        )
        router.wait_ready(timeout_s=120)

        # 1. warm the shared kcache through the router (the ONLY traces)
        warm = router.submit(sweep_doc("fed-warm", 11), tenant="warm")
        router.wait(warm["id"], timeout_s=600)

        # 2. the batch: a burst faster than the probe refresh, so sticky
        # affinity piles team-a onto one peer and the stealer has work
        placed = [
            (router.submit(doc, tenant=tenant), tenant)
            for tenant, doc in batch
        ]
        handles = [out["id"] for out, _ in placed]
        pile_peer = placed[0][0]["peer"]

        # 3. wait for a steal, then SIGKILL the loaded peer mid-sweep
        steals = 0
        deadline = time.time() + 120
        while time.time() < deadline:
            steals = router.metrics()["counters"].get(
                "federation.steals", 0
            )
            if steals >= 1:
                break
            time.sleep(0.2)
        procs[pile_peer].kill()
        procs[pile_peer].wait()

        results: dict[str, dict] = {}
        for h in handles:
            results[h] = router.wait(h, timeout_s=900)
        metrics_doc = router.metrics()
        health = router.health()
        try:
            router.drain()
        except ServeClientError:
            pass
        router_proc.wait(timeout=60)
        for name, proc in procs.items():
            if name == pile_peer:
                continue
            try:
                ServeClient(
                    os.path.join(peers[name], "serve.sock"), timeout=30
                ).drain()
            except ServeClientError:
                pass
            proc.wait(timeout=60)
        wall = time.perf_counter() - t0

    metrics_path = os.path.join(_REPO, "federation_smoke.metrics.json")
    with open(metrics_path, "w") as f:
        json.dump(metrics_doc, f, indent=1)
        f.write("\n")
    # STRICT validation: federation.* must be a registered namespace
    obs_metrics.validate_metrics_doc(metrics_doc, strict_namespaces=True)

    # uninterrupted references: each doc as one in-process fleet
    chains_equal = True
    zero_recompiles = True
    for (tenant, doc), h in zip(batch, handles):
        info = results[h]
        jobs, _ = load_sweep(json.loads(json.dumps(doc)))
        ref = build_fleet(jobs, lanes=2, windows_per_dispatch=2)
        ref.run()
        ref_rows = ref.results()
        rows = info.get("results") or []
        if not (
            info["status"] == "done"
            and [r["name"] for r in rows] == [r["name"] for r in ref_rows]
            and [r.get("audit", {}).get("chain") for r in rows]
            == [r["audit"]["chain"] for r in ref_rows]
        ):
            chains_equal = False
        if (info.get("stats") or {}).get("kernel_traces", -1) != 0:
            zero_recompiles = False

    counters = metrics_doc["counters"]
    gate_steals = counters.get("federation.steals", 0) >= 1
    gate_failover = (
        counters.get("federation.failovers", 0) >= 1
        and counters.get("federation.replayed_sweeps", 0) >= 1
    )
    return {
        "stage": "federation_smoke",
        "hosts": num_hosts,
        "peers": len(peers),
        "sweeps": len(batch),
        "pile_peer": pile_peer,
        "wall_s": round(wall, 3),
        "statuses": {h: results[h]["status"] for h in handles},
        "chains_equal": chains_equal,
        "federation": {
            k: v for k, v in counters.items()
            if k.startswith("federation.")
        },
        "peers_up": health.get("peers_up"),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_chains": bool(chains_equal),
        "gate_zero_recompiles": bool(zero_recompiles),
        "gate_steals": bool(gate_steals),
        "gate_failover": bool(gate_failover),
        "gate": bool(
            chains_equal and zero_recompiles and gate_steals
            and gate_failover
        ),
    }


def stage_pipeline_smoke(hosts: int = 256, msgload: int = 2,
                         stop_s: int = 12, wpd: int = 4,
                         drain_ms: float = 40.0):
    """Pipelined CPU↔TPU handoff gate (ISSUE 15 acceptance).

    Four chain-equality arms prove the two-slot pipeline changes WHEN
    dispatches are enqueued, never what they compute: {conservative,
    optimistic, async-islands, fleet} each run pipelined AND serial
    (`experimental.pipelined_dispatch: false`), audit chains + committed
    events bit-identical per pair.

    The wall-clock arm runs a HANDOFF-HEAVY conservative workload: short
    fused dispatches (small windows_per_dispatch) with a per-handoff
    host-drain model attached through `Simulation.add_handoff_hook` — a
    blocking wait of `drain_ms` standing in for the managed-plane
    syscall drain (procs/bridge.py waits on child-process IPC between
    windows; the pure-device bench has no children, so the wait is
    modeled, and it is WAIT, not host compute — exactly the latency
    class the pipeline hides). Serial pays device + drain per boundary;
    pipelined pays max(device, drain) — the gate demands >= 1.2x.

    Also gated: the schema-v14 metrics artifact (pipeline.* recorded,
    strict-validated), zero kernel retraces on the pipelined arm with
    the same compile count as the serial arm (pipelining must not add a
    compile), and trace-derived overlap efficiency > 0 (the
    issue/await/host_drain spans tools/trace_summary.py reads).

    CPU-deterministic (both arms share one backend), so no backend
    wait."""
    import importlib.util
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.analysis import hlo_audit
    from shadow_tpu.flagship import build_phold_flagship
    from shadow_tpu.fleet import JobSpec, build_fleet
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.obs.trace import ChromeTracer
    from shadow_tpu.sim import build_simulation

    _enable_compile_cache()

    # ---- chain-equality arms (small, shared shapes) ----
    gml = _async_smoke_gml(2, 4)

    def small_cfg(pipelined, **exp):
        hosts_d = {}
        for v in range(8):
            hosts_d[f"h{v:02d}"] = {
                "quantity": 1, "network_node_id": v, "app_model": "phold",
                "app_options": {"msgload": 1, "runtime": 6,
                                "local_span": 2},
            }
        experimental = {
            "event_capacity": 1024, "events_per_host_per_window": 8,
            "outbox_slots": 8, "inbox_slots": 4,
            "pipelined_dispatch": pipelined,
        }
        experimental.update(exp)
        return {
            "general": {"stop_time": 8, "seed": 42},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "experimental": experimental,
            "hosts": hosts_d,
        }

    def chain_of(sim):
        return int(sim.audit_chain()), int(
            sim.counters()["events_committed"]
        )

    arms = {}

    def pair(name, runner, mk):
        piped, serial = mk(True), mk(False)
        runner(piped)
        runner(serial)
        cp, cs = chain_of(piped), chain_of(serial)
        arms[name] = {
            "chain": cp[0], "events": cp[1], "equal": cp == cs,
        }
        return piped

    pair("conservative", lambda s: s.run(windows_per_dispatch=8),
         lambda p: build_simulation(small_cfg(p)))
    pair("optimistic", lambda s: s.run_optimistic(),
         lambda p: build_simulation(small_cfg(p)))
    pair("async_islands", lambda s: s.run(windows_per_dispatch=8),
         lambda p: build_simulation(
             small_cfg(p, num_shards=2, exchange_slots=16)))

    def mk_fleet(pipelined):
        jobs = [
            JobSpec(f"j{i}", small_cfg(pipelined))
            for i in range(3)
        ]
        for i, j in enumerate(jobs):
            j.config["general"]["seed"] = 42 + i  # data-plane sweep axis
        return build_fleet(jobs, lanes=2)

    piped_fleet, serial_fleet = mk_fleet(True), mk_fleet(False)
    piped_fleet.run()
    serial_fleet.run()
    rows_p = {r["name"]: r["audit"]["chain"] for r in piped_fleet.results()}
    rows_s = {r["name"]: r["audit"]["chain"] for r in serial_fleet.results()}
    arms["fleet"] = {
        "chain": rows_p.get("j0", 0),
        "events": sum(
            r["events_committed"] for r in piped_fleet.results()
        ),
        "equal": rows_p == rows_s and bool(rows_p),
    }
    gate_chain = all(a["equal"] for a in arms.values())

    # ---- wall-clock arm: handoff-heavy workload + drain model ----
    drain_s = drain_ms / 1e3

    def drain_model(sim, mn):
        # the managed-plane syscall-drain stand-in: a blocking WAIT at
        # every handoff boundary (state untouched — quiet by contract)
        time.sleep(drain_s)

    def timing_arm(pipelined, tracer=None):
        sim = build_phold_flagship(
            hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s - 1,
            seed=7, pipelined_dispatch=pipelined,
        )
        sim.obs_session = obs_metrics.ObsSession(tracer=tracer)
        # warm the compile, then time the steady region with the drain
        sim.run(until=2 * simtime.NS_PER_SEC, windows_per_dispatch=wpd)
        sim.add_handoff_hook(drain_model)
        t0 = time.perf_counter()
        sim.run(windows_per_dispatch=wpd)
        wall = time.perf_counter() - t0
        return sim, wall

    # interleave arms to decorrelate machine drift from the comparison
    serial_sim, w_s = timing_arm(False)
    tracer = ChromeTracer()
    piped_sim, w_p = timing_arm(True, tracer=tracer)
    w_s = min(w_s, timing_arm(False)[1])
    w_p = min(w_p, timing_arm(True)[1])
    timing_equal = chain_of(piped_sim) == chain_of(serial_sim)
    gate_wall = w_p > 0 and (w_s / w_p) >= 1.2

    # retrace-free: pipelining must not add a compile — one lowering per
    # bound kernel, and the same compile count as the serial arm
    retrace_p = hlo_audit.retrace_report(piped_sim)
    retrace_s = hlo_audit.retrace_report(serial_sim)
    gate_retrace = bool(
        retrace_p["ok"]
        and retrace_p["compiles_total"] == retrace_s["compiles_total"]
    )

    # trace-derived overlap efficiency (tools/trace_summary.py)
    spec = importlib.util.spec_from_file_location(
        "trace_summary", os.path.join(_REPO, "tools", "trace_summary.py")
    )
    trace_summary = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_summary)
    overlap = trace_summary.overlap_stats(tracer.to_doc()) or {}

    # schema-v14 artifact from the pipelined timing arm
    metrics_path = os.path.join(_REPO, "pipeline_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(piped_sim)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "pipeline_smoke", "hosts": hosts,
        "drain_model_ms": drain_ms,
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    pstats = piped_sim.pipeline_stats()
    gate_schema = bool(
        doc["counters"].get("pipeline.issued_ahead", 0) > 0
        and doc["counters"].get("pipeline.overlap_ns", 0) > 0
    )

    return {
        "stage": "pipeline_smoke",
        "platform": jax.default_backend(),
        "hosts": hosts,
        "windows_per_dispatch": wpd,
        "host_drain_model_ms": drain_ms,
        "arms": arms,
        "timing_chain_equal": bool(timing_equal),
        "wall_serial_s": round(w_s, 3),
        "wall_pipelined_s": round(w_p, 3),
        "wall_ratio": round(w_s / w_p, 2) if w_p else 0.0,
        "pipeline": {k: int(v) for k, v in sorted(pstats.items())},
        "overlap_efficiency": round(
            float(overlap.get("overlap_efficiency", 0.0)), 3
        ),
        "kernel_compiles": int(retrace_p["compiles_total"]),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_chain": bool(gate_chain and timing_equal),
        "gate_wall": bool(gate_wall),
        "gate_retrace": gate_retrace,
        "gate_schema": gate_schema,
        "gate": bool(
            gate_chain and timing_equal and gate_wall and gate_retrace
            and gate_schema
        ),
    }


def stage_hostplane_smoke(hosts: int = 48, msgload: int = 2,
                          stop_s: int = 12, wpd: int = 4,
                          per_host_drain_ms: float = 1.0):
    """Multi-worker host-plane gate (ISSUE 17 acceptance).

    Five chain-equality arms prove the host plane changes WHO executes
    partition-local handoff work, never what it computes or the order it
    commits: {conservative, optimistic, async-islands, fleet,
    pipelined-conservative} each run with `experimental.host_workers: 4`
    AND the serial path (`host_workers: 1`), audit chains + committed
    events bit-identical per pair — and every pair registers a sharded
    recorder hook whose (frontier, gid) coverage must match exactly,
    proving the fan-out visits the same partitions either way.

    The wall-clock arm runs a HANDOFF-HEAVY conservative workload: a
    per-host drain model attached through
    `Simulation.add_handoff_hook(fn, sharded=True)` — a blocking wait of
    `per_host_drain_ms` PER HOST standing in for partition-local
    syscall/IPC servicing (the latency class PARSIR binds to per-worker
    queues). The serial arm pays hosts x wait per boundary; the 4-worker
    arm pays ~hosts/4 x wait — the gate demands >= 1.2x overall wall.

    Also gated: the schema-v15 metrics artifact (hostplane.* recorded
    with sharded_drains > 0 and ZERO serial_fallbacks,
    strict-validated), zero kernel retraces with the SAME compile count
    as the serial arm (the host plane never touches the device program),
    and trace-derived drain parallelism > 1 from the per-worker
    host_drain spans tools/trace_summary.py reads.

    CPU-deterministic (all arms share one backend), so no backend
    wait."""
    import importlib.util
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.analysis import hlo_audit
    from shadow_tpu.flagship import build_phold_flagship
    from shadow_tpu.fleet import JobSpec, build_fleet
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.obs.trace import ChromeTracer
    from shadow_tpu.sim import build_simulation

    _enable_compile_cache()

    # ---- chain-equality arms (small, shared shapes) ----
    gml = _async_smoke_gml(2, 4)

    def small_cfg(workers, **exp):
        hosts_d = {}
        for v in range(8):
            hosts_d[f"h{v:02d}"] = {
                "quantity": 1, "network_node_id": v, "app_model": "phold",
                "app_options": {"msgload": 1, "runtime": 6,
                                "local_span": 2},
            }
        experimental = {
            "event_capacity": 1024, "events_per_host_per_window": 8,
            "outbox_slots": 8, "inbox_slots": 4,
            "host_workers": workers,
        }
        experimental.update(exp)
        return {
            "general": {"stop_time": 8, "seed": 42},
            "network": {"graph": {"type": "gml", "inline": gml}},
            "experimental": experimental,
            "hosts": hosts_d,
        }

    def chain_of(sim):
        return int(sim.audit_chain()), int(
            sim.counters()["events_committed"]
        )

    arms = {}

    def pair(name, runner, mk):
        multi, serial = mk(4), mk(1)
        hits_m, hits_s = [], []
        multi.add_handoff_hook(
            lambda s, mn, gid, h=hits_m: h.append((int(mn), int(gid))),
            sharded=True,
        )
        serial.add_handoff_hook(
            lambda s, mn, gid, h=hits_s: h.append((int(mn), int(gid))),
            sharded=True,
        )
        runner(multi)
        runner(serial)
        cm, cs = chain_of(multi), chain_of(serial)
        hp = multi.hostplane_stats()
        arms[name] = {
            "chain": cm[0], "events": cm[1],
            "equal": bool(
                cm == cs
                and sorted(hits_m) == sorted(hits_s)
                and bool(hits_m)
                and hp.get("sharded_drains", 0) > 0
                and serial.hostplane_stats() == {}
            ),
        }
        return multi

    pair("conservative", lambda s: s.run(windows_per_dispatch=8),
         lambda w: build_simulation(small_cfg(w)))
    pair("optimistic", lambda s: s.run_optimistic(),
         lambda w: build_simulation(small_cfg(w)))
    pair("async_islands", lambda s: s.run(windows_per_dispatch=8),
         lambda w: build_simulation(
             small_cfg(w, num_shards=2, exchange_slots=16)))
    pair("conservative_pipelined",
         lambda s: s.run(windows_per_dispatch=8),
         lambda w: build_simulation(small_cfg(w, pipelined_dispatch=True)))

    def mk_fleet(workers):
        jobs = [
            JobSpec(f"j{i}", small_cfg(workers))
            for i in range(3)
        ]
        for i, j in enumerate(jobs):
            j.config["general"]["seed"] = 42 + i  # data-plane sweep axis
        return build_fleet(jobs, lanes=2)

    multi_fleet, serial_fleet = mk_fleet(4), mk_fleet(1)
    lane_hits_m, lane_hits_s = [], []
    multi_fleet.add_handoff_hook(
        lambda f, mn, lane, h=lane_hits_m: h.append(int(lane)),
        sharded=True,
    )
    serial_fleet.add_handoff_hook(
        lambda f, mn, lane, h=lane_hits_s: h.append(int(lane)),
        sharded=True,
    )
    multi_fleet.run()
    serial_fleet.run()
    rows_m = {r["name"]: r["audit"]["chain"] for r in multi_fleet.results()}
    rows_s = {r["name"]: r["audit"]["chain"] for r in serial_fleet.results()}
    arms["fleet"] = {
        "chain": rows_m.get("j0", 0),
        "events": sum(
            r["events_committed"] for r in multi_fleet.results()
        ),
        "equal": bool(
            rows_m == rows_s and bool(rows_m)
            and sorted(lane_hits_m) == sorted(lane_hits_s)
            and multi_fleet.hostplane_stats().get("sharded_drains", 0) > 0
        ),
    }
    gate_chain = all(a["equal"] for a in arms.values())

    # ---- wall-clock arm: handoff-heavy workload + per-host drain ----
    drain_s = per_host_drain_ms / 1e3

    def drain_model(sim, mn, gid):
        # the partition-local syscall-drain stand-in: a blocking WAIT
        # per host at every handoff boundary (state untouched — quiet
        # and partition-local by contract, so the plane may shard it)
        time.sleep(drain_s)

    def timing_arm(workers, tracer=None):
        sim = build_phold_flagship(
            hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s - 1,
            seed=7, host_workers=workers,
        )
        sim.obs_session = obs_metrics.ObsSession(tracer=tracer)
        # warm the compile, then time the steady region with the drain
        sim.run(until=2 * simtime.NS_PER_SEC, windows_per_dispatch=wpd)
        sim.add_handoff_hook(drain_model, sharded=True)
        t0 = time.perf_counter()
        sim.run(windows_per_dispatch=wpd)
        wall = time.perf_counter() - t0
        return sim, wall

    # interleave arms to decorrelate machine drift from the comparison
    serial_sim, w_s = timing_arm(1)
    tracer = ChromeTracer()
    multi_sim, w_m = timing_arm(4, tracer=tracer)
    w_s = min(w_s, timing_arm(1)[1])
    w_m = min(w_m, timing_arm(4)[1])
    timing_equal = chain_of(multi_sim) == chain_of(serial_sim)
    gate_wall = w_m > 0 and (w_s / w_m) >= 1.2

    # retrace-free: the host plane must not add a compile — one lowering
    # per bound kernel, and the same compile count as the serial arm
    retrace_m = hlo_audit.retrace_report(multi_sim)
    retrace_s = hlo_audit.retrace_report(serial_sim)
    gate_retrace = bool(
        retrace_m["ok"]
        and retrace_m["compiles_total"] == retrace_s["compiles_total"]
    )

    # trace-derived drain parallelism (tools/trace_summary.py)
    spec = importlib.util.spec_from_file_location(
        "trace_summary", os.path.join(_REPO, "tools", "trace_summary.py")
    )
    trace_summary = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_summary)
    drain = trace_summary.drain_parallelism(tracer.to_doc()) or {}

    # schema-v15 artifact from the 4-worker timing arm
    metrics_path = os.path.join(_REPO, "hostplane_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(multi_sim)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "hostplane_smoke", "hosts": hosts,
        "per_host_drain_ms": per_host_drain_ms,
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    hpstats = multi_sim.hostplane_stats()
    gate_schema = bool(
        doc["counters"].get("hostplane.workers", 0) == 4
        and doc["counters"].get("hostplane.sharded_drains", 0) > 0
        and doc["counters"].get("hostplane.serial_fallbacks", -1) == 0
    )

    return {
        "stage": "hostplane_smoke",
        "platform": jax.default_backend(),
        "hosts": hosts,
        "windows_per_dispatch": wpd,
        "per_host_drain_ms": per_host_drain_ms,
        "arms": arms,
        "timing_chain_equal": bool(timing_equal),
        "wall_serial_s": round(w_s, 3),
        "wall_multi_s": round(w_m, 3),
        "wall_ratio": round(w_s / w_m, 2) if w_m else 0.0,
        "hostplane": {k: int(v) for k, v in sorted(hpstats.items())},
        "drain_parallelism": round(
            float(drain.get("parallelism", 0.0)), 2
        ),
        "kernel_compiles": int(retrace_m["compiles_total"]),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_chain": bool(gate_chain and timing_equal),
        "gate_wall": bool(gate_wall),
        "gate_parallel": bool(drain.get("parallelism", 0.0) > 1.0),
        "gate_retrace": gate_retrace,
        "gate_schema": gate_schema,
        "gate": bool(
            gate_chain and timing_equal and gate_wall
            and drain.get("parallelism", 0.0) > 1.0 and gate_retrace
            and gate_schema
        ),
    }


def stage_qdisc_smoke(stop_s: int = 3, wpd: int = 8):
    """Per-interface scheduling-plane gate (ISSUE 19 acceptance).

    Arms, all CPU-deterministic (no backend wait):

    - default-compat: the SAME overloaded flood run three ways — no
      qdisc section, explicit `qdisc: {discipline: fifo}`, and the
      legacy `experimental.interface_qdisc: fifo` string — must produce
      bit-identical audit chains (the discipline-interface reroute of
      nic.py's send ring is invisible to default runs).
    - eiffel-vs-exact: the bucketed discipline in its exactness regime
      (fifo rank → rank spread 0 < B) against exact PIFO: chains AND the
      full qdisc.* counter plane (enqueues/drops/sojourn) bit-identical.
    - driver matrix: one pifo+wfq+codel config chain-identical under
      {conservative, optimistic, async-islands(2), fleet} — the queue
      plane is ordinary [H]-leading sub-state, so every execution engine
      composes.
    - separation: a bandwidth-starved udp_echo bufferbloat workload
      (64-deep drop-tail ring vs pifo with the CoDel drop hook): the
      FIFO arm's mean RTT must exceed the CoDel arm's by >= 1.5x —
      the scheduling plane visibly changes end-to-end behavior, not
      just counters.
    - retrace-free + schema: zero kernel retraces on the pifo arm, and
      its metrics artifact strict-validates at schema v17 with live
      qdisc.* counters."""
    import jax
    import numpy as np

    from shadow_tpu.analysis import hlo_audit
    from shadow_tpu.fleet import JobSpec, build_fleet
    from shadow_tpu.obs import metrics as obs_metrics
    from shadow_tpu.sim import build_simulation

    _enable_compile_cache()

    # 400B datagram = 428B wire = ~34 ms at 100 Kbit, sent every 5 ms:
    # the send queue must absorb a 7x overload
    gml_slow = (
        'graph [ node [ id 0 bandwidth_down "10 Mbit" '
        'bandwidth_up "100 Kbit" ] '
        'edge [ source 0 target 0 latency "10 ms" packet_loss 0.0 ] ]'
    )

    def flood_cfg(qdisc=None, seed=6, **exp):
        experimental = {
            "event_capacity": 4096, "events_per_host_per_window": 8,
        }
        experimental.update(exp)
        cfg = {
            "general": {"stop_time": stop_s, "seed": seed},
            "network": {"graph": {"type": "gml", "inline": gml_slow}},
            "experimental": experimental,
            "hosts": {
                "server": {"app_model": "udp_flood",
                           "app_options": {"role": "server"},
                           "bandwidth_down": "10 Mbit",
                           "bandwidth_up": "10 Mbit"},
                "client": {"quantity": 3, "app_model": "udp_flood",
                           "app_options": {"interval": "5 ms",
                                           "size": 400,
                                           "runtime": stop_s - 1}},
            },
        }
        if qdisc:
            cfg["qdisc"] = qdisc
        return cfg

    def chain_of(sim):
        return int(sim.audit_chain()), int(
            sim.counters()["events_committed"]
        )

    def run(cfg, runner=None):
        sim = build_simulation(cfg)
        if runner is None:
            sim.run(windows_per_dispatch=wpd)
        else:
            runner(sim)
        return sim

    # ---- default-compat arm ----
    c_none = chain_of(run(flood_cfg()))
    c_section = chain_of(run(flood_cfg(qdisc={"discipline": "fifo"})))
    c_legacy = chain_of(run(flood_cfg(interface_qdisc="fifo")))
    gate_default = c_none == c_section == c_legacy

    # ---- eiffel-vs-exact parity arm (rank spread 0 < B = 8) ----
    pifo_sim = run(flood_cfg(qdisc={"discipline": "pifo",
                                    "queue_slots": 32}))
    eiffel_sim = run(flood_cfg(qdisc={"discipline": "eiffel",
                                      "queue_slots": 32, "buckets": 8}))
    qp = jax.device_get(pifo_sim.state.subs["qdisc"])
    qe = jax.device_get(eiffel_sim.state.subs["qdisc"])
    counter_keys = ("enqueues", "dequeues", "drops_overflow", "drops_red",
                    "drops_codel", "sojourn_sum", "depth_peak")
    counters_equal = all(
        bool((np.asarray(qp[k]) == np.asarray(qe[k])).all())
        for k in counter_keys
    )
    gate_eiffel = bool(
        chain_of(pifo_sim) == chain_of(eiffel_sim) and counters_equal
    )

    # ---- driver matrix arm ----
    qfull = {"discipline": "pifo", "rank": "wfq", "drop": "codel",
             "queue_slots": 32}
    cons_sim = run(flood_cfg(qdisc=qfull))
    c_cons = chain_of(cons_sim)
    c_opt = chain_of(run(flood_cfg(qdisc=qfull),
                         runner=lambda s: s.run_optimistic()))
    c_isl = chain_of(run(flood_cfg(qdisc=qfull, num_shards=2,
                                   exchange_slots=16)))
    jobs = [JobSpec(f"j{i}", flood_cfg(qdisc=qfull, seed=6 + i))
            for i in range(2)]
    fl = build_fleet(jobs, lanes=2)
    fl.run()
    rows = {r["name"]: (r["audit"]["chain"], r["events_committed"])
            for r in fl.results()}
    gate_drivers = bool(
        c_cons == c_opt == c_isl and rows.get("j0") == c_cons
    )

    # ---- separation arm: bufferbloat RTT, drop-tail vs CoDel ----
    gml_echo = (
        'graph [ '
        'node [ id 0 bandwidth_down "10 Mbit" bandwidth_up "10 Mbit" ] '
        'node [ id 1 bandwidth_down "10 Mbit" bandwidth_up "500 Kbit" ] '
        'edge [ source 0 target 0 latency "1 ms" packet_loss 0.0 ] '
        'edge [ source 1 target 1 latency "1 ms" packet_loss 0.0 ] '
        'edge [ source 0 target 1 latency "5 ms" packet_loss 0.0 ] ]'
    )

    def echo_cfg(qdisc=None):
        cfg = {
            "general": {"stop_time": 8, "seed": 5},
            "network": {"graph": {"type": "gml", "inline": gml_echo}},
            "experimental": {"event_capacity": 4096,
                             "events_per_host_per_window": 8},
            "hosts": {
                "server": {"network_node_id": 0, "app_model": "udp_echo",
                           "app_options": {"role": "server"}},
                "client": {"network_node_id": 1, "app_model": "udp_echo",
                           "app_options": {"interval": "2 ms",
                                           "size": 512, "runtime": 6}},
            },
        }
        if qdisc:
            cfg["qdisc"] = qdisc
        return cfg

    def rtt_mean_ms(sim):
        sub = jax.device_get(sim.state.subs["udp_echo"])
        n = int(np.sum(np.asarray(sub["rtt_count"])))
        return (
            float(np.sum(np.asarray(sub["rtt_sum"]))) / n / 1e6
            if n else 0.0
        )

    rtt_fifo = rtt_mean_ms(run(echo_cfg()))
    codel_sim = run(echo_cfg({"discipline": "pifo", "drop": "codel"}))
    rtt_codel = rtt_mean_ms(codel_sim)
    gate_separation = bool(
        rtt_codel > 0 and rtt_fifo >= 1.5 * rtt_codel
    )

    # ---- retrace + schema arms (on the full-feature pifo sim) ----
    retrace = hlo_audit.retrace_report(cons_sim)
    gate_retrace = bool(retrace["ok"])

    metrics_path = os.path.join(_REPO, "qdisc_smoke.metrics.json")
    session = obs_metrics.ObsSession()
    session.finalize(cons_sim)
    doc = session.metrics.dump(metrics_path, meta={
        "stage": "qdisc_smoke", "discipline": "pifo", "rank": "wfq",
        "drop": "codel",
    })
    obs_metrics.validate_metrics_doc(doc, strict_namespaces=True)
    gate_schema = bool(
        doc["schema_version"] == obs_metrics.SCHEMA_VERSION
        and doc["counters"].get("qdisc.enqueues", 0) > 0
        and doc["counters"].get("qdisc.dequeues", 0) > 0
    )

    return {
        "stage": "qdisc_smoke",
        "platform": jax.default_backend(),
        "chain": c_cons[0],
        "events": c_cons[1],
        "rtt_fifo_ms": round(rtt_fifo, 2),
        "rtt_codel_ms": round(rtt_codel, 2),
        "rtt_ratio": round(rtt_fifo / rtt_codel, 2) if rtt_codel else 0.0,
        "qdisc": {k: int(np.sum(np.asarray(qp[k])))
                  for k in counter_keys},
        "kernel_compiles": int(retrace["compiles_total"]),
        "metrics_out": os.path.relpath(metrics_path, _REPO),
        "gate_default": bool(gate_default),
        "gate_eiffel": gate_eiffel,
        "gate_drivers": gate_drivers,
        "gate_separation": gate_separation,
        "gate_retrace": gate_retrace,
        "gate_schema": gate_schema,
        "gate": bool(
            gate_default and gate_eiffel and gate_drivers
            and gate_separation and gate_retrace and gate_schema
        ),
    }


def stage_lint_smoke():
    """shadowlint gate (ISSUE 7 acceptance, extended by ISSUE 14): all
    FOUR static-analysis passes over the tree must report ZERO
    non-baselined violations — the STL0xx AST rules, the SLC0xx
    cross-plane contract auditor, the STH0xx host-thread race lint, and
    the HLO budget ledger (every kernel variant this box can lower,
    against shadow_tpu/analysis/hlo_baseline.json) — and a tiny geared
    driver run must show no kernel retraces (one lowering per bound
    kernel — the compile-cache-miss perf-bug class from r03–r05).
    Pure CPU (AST walks + tiny compiles), so no backend wait."""
    from shadow_tpu.analysis import contracts, hlo_audit, linter, threads
    from shadow_tpu.flagship import build_phold_flagship

    paths = [os.path.join(_REPO, p) for p in ("shadow_tpu", "tools", "bench.py")]
    findings = linter.lint_paths(paths, _REPO)
    findings += contracts.audit_tree(_REPO)
    findings += threads.lint_threads_paths(_REPO)
    # the HLO budget ledger: a missing/corrupt baseline is a gate
    # failure with a remediation hint, not a traceback
    hlo_problems = []
    hlo_baseline_ok = True
    try:
        hlo_baseline = hlo_audit.load_hlo_baseline(
            hlo_audit.baseline_path(_REPO)
        )
    except hlo_audit.HloBaselineError as e:
        hlo_baseline_ok = False
        hlo_problems = [str(e)]
    if hlo_baseline_ok:
        ledger = hlo_audit.budget_ledger(
            hlo_audit.default_ledger_variants()
        )
        hlo_problems = hlo_audit.check_ledger(ledger, hlo_baseline)
    findings += [
        linter.Finding(
            path="shadow_tpu/analysis/hlo_baseline.json", line=1, col=0,
            code="SLH001", message=p, text=p.split(":", 1)[0],
        )
        for p in hlo_problems
    ]
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.code))
    baseline = linter.load_baseline(os.path.join(_REPO, linter.BASELINE_NAME))
    new, old = linter.split_baselined(findings, baseline)
    scanned = list(linter.iter_python_files(paths))
    pass_of = {"STL": "lint", "SLC": "contracts", "STH": "threads",
               "SLH": "hlo"}
    passes = {"lint": 0, "contracts": 0, "threads": 0, "hlo": 0}
    for f in new:
        passes[pass_of[f.code[:3]]] += 1
    doc = linter.findings_doc(new, old, scanned, passes=passes)
    report_path = os.path.join(_REPO, "lint_smoke.report.json")
    with open(report_path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")

    # retrace smoke: a geared conservative run + an optimistic run, every
    # bound kernel lowered at most once (hlo_audit retrace detector)
    sim = build_phold_flagship(
        64, msgload=2, stop_s=2, runtime_s=2, seed=3, event_capacity=4096,
        pool_gears=2)
    sim.run()
    retrace = hlo_audit.retrace_report(sim)
    return {
        "stage": "lint_smoke",
        "files_scanned": len(scanned),
        "findings_new": len(new),
        "findings_grandfathered": len(old),
        "by_code": doc["counts"]["by_code"],
        "passes": passes,
        "retrace_ok": bool(retrace["ok"]),
        "kernel_compiles": int(retrace["compiles_total"]),
        "report_out": os.path.relpath(report_path, _REPO),
        "gate_lint": passes["lint"] == 0,
        "gate_contracts": passes["contracts"] == 0,
        "gate_threads": passes["threads"] == 0,
        "gate_hlo_ledger": bool(hlo_baseline_ok and passes["hlo"] == 0),
        "gate_retrace": bool(retrace["ok"]),
        "gate": bool(not new and hlo_baseline_ok and retrace["ok"]),
    }


def main():
    if "--lint-smoke" in sys.argv:
        # static-analysis gate: all four shadowlint passes clean (AST
        # rules, contract auditor, thread race lint, HLO budget ledger)
        # + no kernel retraces. AST walks + tiny CPU compiles — no
        # accelerator, so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_lint_smoke()), flush=True)
        return
    if "--pipeline-smoke" in sys.argv:
        # pipelined-handoff gate: audit chains bit-identical pipelined
        # vs serial across {conservative, optimistic, async-islands,
        # fleet}, >= 1.2x wall on a handoff-heavy workload (the modeled
        # managed-plane drain hidden behind in-flight device work),
        # schema-v14 artifact, retrace-free. Both arms share one CPU
        # backend — no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_pipeline_smoke()), flush=True)
        return
    if "--hostplane-smoke" in sys.argv:
        # multi-worker host-plane gate: audit chains bit-identical
        # host_workers=4 vs 1 across {conservative, optimistic,
        # async-islands, fleet, pipelined}, >= 1.2x wall on a
        # handoff-heavy workload (the per-host drain model sharded
        # across pinned workers), schema-v15 artifact, drain
        # parallelism > 1, retrace-free. All arms share one CPU
        # backend — no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_hostplane_smoke()), flush=True)
        return
    if "--qdisc-smoke" in sys.argv:
        # per-interface scheduling gate: default-FIFO arm bit-identical
        # to pre-qdisc runs, eiffel-vs-exact chain parity, one pifo
        # config chain-identical across {conservative, optimistic,
        # islands, fleet}, drop-tail-vs-CoDel RTT separation on a
        # bufferbloat workload, retrace-free, schema-v17 artifact.
        # CPU-deterministic by design, so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_qdisc_smoke()), flush=True)
        return
    if "--serve-smoke" in sys.argv:
        # sim-as-a-service gate: submit → SIGKILL the daemon → restart →
        # journal replay finishes the sweep with bit-identical audit
        # chains and ZERO kernel retraces off the warm AOT cache. CPU-
        # deterministic by design, so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_serve_smoke()), flush=True)
        return
    if "--federation-smoke" in sys.argv:
        # federated serve gate: 3 peers + router sharing one kcache
        # root, mixed-tenant batch, steal under affinity pile-up,
        # SIGKILL one peer mid-sweep → journal-replay failover onto the
        # survivors with bit-identical chains and zero retraces.
        # CPU-deterministic by design, so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_federation_smoke()), flush=True)
        return
    if "--async-smoke" in sys.argv:
        # async conservative-sync gate: per-shard frontiers beat the
        # window barrier on an imbalanced islands workload with a
        # bit-identical audit chain. Both arms run the same backend, so
        # the comparison is CPU-deterministic — no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_async_smoke()), flush=True)
        return
    if "--profile-smoke" in sys.argv:
        # shadowscope gate: profiler-on vs off bit-identical chains at
        # <=3% overhead, critical-path attribution naming the skewed
        # shard, exact two-peer /timez histogram folds, and a strict-
        # validated schema-current artifact carrying prof.* keys. Both
        # arms share one CPU backend — no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_profile_smoke()), flush=True)
        return
    if "--mesh-smoke" in sys.argv:
        # true multi-chip gate: shard_map mesh execution with
        # neighbor-only ppermute frontier exchange + min-cut placement —
        # chains bit-identical to the single-program islands run, zero
        # all-gathers in the mesh kernel, collective volume scaling with
        # in-edge degree, retrace-free across a gear shift and a live
        # migration. Runs on 8 VIRTUAL CPU devices (the force must land
        # before the jax backend initializes), so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        from shadow_tpu.parallel.virtualize import force_cpu_devices

        force_cpu_devices(8, cache_dir=os.path.join(_REPO, ".jax_cache"))
        print(json.dumps(stage_mesh_smoke()), flush=True)
        return
    if "--mesh-resilience-smoke" in sys.argv:
        # elastic-resilience gate: kill_chip mid-run → drain → relayout
        # onto the surviving mesh → re-expand on recovery, chains
        # bit-identical to the uninterrupted run (and to the wait-policy
        # control arm); the shrink-to-1 arm resumes on the global
        # engine. Runs on 8 VIRTUAL CPU devices (the force must land
        # before the jax backend initializes), so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        from shadow_tpu.parallel.virtualize import force_cpu_devices

        force_cpu_devices(8, cache_dir=os.path.join(_REPO, ".jax_cache"))
        print(json.dumps(stage_mesh_resilience_smoke()), flush=True)
        return
    if "--balance-smoke" in sys.argv:
        # self-balancing gate: a skew_hosts-driven hot shard is detected
        # and healed by a verified live migration — lower frontier
        # spread + fewer blocked supersteps than the balancer-off arm,
        # bit-identical chains (incl. a forced mid-migration rollback),
        # zero retraces. All arms share one CPU backend — no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_balance_smoke()), flush=True)
        return
    if "--pressure-smoke" in sys.argv:
        # pressure-plane gate: exhaust_backend / saturate_pool injections
        # engage the degradation ladder and the run completes with the
        # uninterrupted chain. CPU-deterministic (the injection IS the
        # pressure), so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_pressure_smoke()), flush=True)
        return
    if "--resilience-smoke" in sys.argv:
        # backend-survivability gate: deterministic kill_backend → drain /
        # resume / CPU failover with bit-identical audit chains. CPU-
        # deterministic (the injection is the outage), so no backend wait.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_resilience_smoke()), flush=True)
        return
    if "--fault-smoke" in sys.argv:
        # fault-tolerance gate: quarantine-mode run with one injected
        # process kill completes rc=0 and records faults.* metrics.
        # Managed plane only — no accelerator, so no backend wait.
        print(json.dumps(stage_fault_smoke()), flush=True)
        return
    if "--fleet-smoke" in sys.argv:
        # fleet gate: 8 mixed-length jobs as ONE device program — one
        # window-kernel compile, fleet wall < summed solo wall. A CPU
        # gate by design (compile amortization is the point), so no
        # backend wait: jax's CPU backend always answers.
        os.environ.setdefault("SHADOW_TPU_BENCH_ALLOW_CPU", "1")
        print(json.dumps(stage_fleet_smoke()), flush=True)
        return
    if not wait_for_backend():
        # No backend after the full retry budget: record the failure as a
        # schema-valid JSON artifact — ok:false + reason + probe timeline +
        # the requested platform — printed LAST so the stored output tail
        # stays machine-parseable (BENCH_r03-r05 recorded rc=1 text tails
        # only), and exit 0: the artifact IS the result of this round.
        _emit_backend_unavailable()
        return

    try:
        _run_stages()
    except BackendUnavailable as e:
        # Backend died MID-run and the recovery probe budget ran out: the
        # exhaustion artifact carries ok:false with rc 0 on this path too
        # (r05 still recorded rc:1 here).
        _emit_backend_unavailable(detail=str(e))


def _emit_backend_unavailable(detail: str | None = None) -> None:
    artifact = {
        "metric": "backend_unavailable", "value": 0, "unit": "none",
        "vs_baseline": 0,
        "ok": False,
        "reason": "backend_unavailable",
        "platform": os.environ.get("JAX_PLATFORMS", "unknown"),
        "probe_timeline": _PROBE_LOG,
    }
    if detail:
        artifact["detail"] = detail[-300:]
    print(json.dumps(artifact), flush=True)


def _run_stages():
    if "--stages" in sys.argv:
        # staged measurement configs (BASELINE.md 2-3); one JSON line each
        print(json.dumps(_with_backend_retry(stage_udp_flood)))
        print(json.dumps(_with_backend_retry(stage_tcp_bulk)))
        return
    if "--stages-100k" in sys.argv:
        # BASELINE configs 4-5 SHAPE at one-chip scale (VERDICT r3 #3)
        print(json.dumps(_with_backend_retry(stage_phold_100k)))
        print(json.dumps(_with_backend_retry(stage_udp_flood_100k)))
        return
    if "--shard-sweep" in sys.argv:
        shard_sweep(out_path=os.path.join(_REPO, "docs", "shard_sweep.json"))
        return
    if "--obs-smoke" in sys.argv:
        # telemetry-plane overhead gate (<= 3% step time with counters on)
        print(json.dumps(_with_backend_retry(stage_obs_overhead)), flush=True)
        return
    if "--audit-smoke" in sys.argv:
        # determinism-audit gate (<= 3% step time with digest chain +
        # flight ring compiled in; identical chains across seeded reruns;
        # the bisector pinpoints a forged divergence)
        print(json.dumps(_with_backend_retry(stage_audit_smoke)), flush=True)
        return
    if "--gear-smoke" in sys.argv:
        # occupancy-adaptive gearing gate (>= 25% per-window win with the
        # pool oversized 8x above steady-state occupancy)
        print(json.dumps(_with_backend_retry(stage_gear_win)), flush=True)
        return
    if "--stages-50k" in sys.argv:
        # BASELINE config 4 rows: both synchronization modes, on the
        # global engine AND the islands runner (r5: optimistic×islands),
        # plus the undersized-pool spill-cost row (VERDICT r4 #6)
        print(json.dumps(_with_backend_retry(stage_udp_flood_50k,
                                             "conservative")), flush=True)
        print(json.dumps(_with_backend_retry(stage_udp_flood_50k,
                                             "optimistic")), flush=True)
        print(json.dumps(_with_backend_retry(
            stage_udp_flood_50k, "conservative", num_shards=8)), flush=True)
        print(json.dumps(_with_backend_retry(
            stage_udp_flood_50k, "optimistic", num_shards=8)), flush=True)
        print(json.dumps(_with_backend_retry(stage_spill_50k)), flush=True)
        return

    num_hosts, msgload, stop_s = 16384, 8, 10
    dev_events, dev_wall, sim_per_wall = _with_backend_retry(
        device_phold, num_hosts, msgload, stop_s
    )
    dev_rate = dev_events / dev_wall if dev_wall > 0 else 0.0

    base = cpp_phold_baseline(num_hosts, msgload, stop_s)
    base_rate = base["events_per_sec"] or 1.0

    print(
        json.dumps(
            {
                "metric": "phold_committed_events_per_sec_per_chip",
                "value": round(dev_rate, 1),
                "unit": "events/sec",
                "vs_baseline": round(dev_rate / base_rate, 3),
                "detail": {
                    "hosts": num_hosts,
                    "msgload": msgload,
                    "sim_seconds": stop_s,
                    "device_events": int(dev_events),
                    "device_wall_s": round(dev_wall, 3),
                    "sim_sec_per_wall_sec": round(sim_per_wall, 2),
                    "baseline": base,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
