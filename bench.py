"""Benchmark: on-device PHOLD throughput vs a CPU sequential-DES baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The workload is the PHOLD PDES canary (reference src/test/phold/phold.yaml:
peers over a 50ms self-loop link exchanging random-destination messages),
scaled up. `value` is committed events/sec on the device for the full fused
run (one XLA while_loop program). `vs_baseline` is the speedup over the
reference-replica C++ scheduler (native/baseline/phold_baseline.cpp): the
reference itself cannot build in this image (its config/worker layer needs
cargo/rustc, plus glib and igraph — none present, zero egress), so the
replica reimplements its exact hot path — per-host locked priority queues,
worker threads, conservative windows, (time,dst,src,seq) total order — in
C++ at -O2 and runs the same PHOLD workload on this machine's CPU.
"""

from __future__ import annotations

import json
import os
import subprocess
import time


def _enable_compile_cache():
    """Persistent XLA compile cache: the staged configs compile multi-minute
    programs; cache them next to the repo so reruns start in seconds."""
    import jax

    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


_enable_compile_cache()


def device_phold(num_hosts: int, msgload: int, stop_s: int,
                 windows_per_dispatch: int = 64):
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.flagship import build_phold_flagship

    sim = build_phold_flagship(
        num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s
    )
    # Warm-up compile (cached), then timed run.
    sim.run(until=int(0.2 * simtime.NS_PER_SEC),
            windows_per_dispatch=windows_per_dispatch)
    jax.block_until_ready(sim.state.pool.time)
    t0 = time.perf_counter()
    sim.run(windows_per_dispatch=windows_per_dispatch)
    jax.block_until_ready(sim.state.pool.time)
    wall = time.perf_counter() - t0
    c = sim.counters()
    return c["events_committed"], wall, stop_s / wall


_REPO = os.path.dirname(os.path.abspath(__file__))
_BASELINE_SRC = os.path.join(_REPO, "native", "baseline", "phold_baseline.cpp")
_BASELINE_BIN = os.path.join(_REPO, "native", "build", "phold_baseline")


def cpp_phold_baseline(num_hosts: int, msgload: int, stop_s: int,
                       workers: int = 0):
    """Run the reference-replica C++ scheduler (see module docstring) on the
    same PHOLD parameters; returns its parsed JSON result. workers=0 means
    one per online CPU (the reference's recommended parallelism,
    configuration.rs:141-147)."""
    if not os.path.exists(_BASELINE_BIN) or (
        os.path.getmtime(_BASELINE_BIN) < os.path.getmtime(_BASELINE_SRC)
    ):
        os.makedirs(os.path.dirname(_BASELINE_BIN), exist_ok=True)
        subprocess.run(
            ["g++", "-O2", "-pthread", "-o", _BASELINE_BIN, _BASELINE_SRC],
            check=True,
        )
    # runtime == stop: hosts forward for the whole run, matching
    # device_phold's build (runtime_s=stop_s).
    out = subprocess.run(
        [_BASELINE_BIN, str(num_hosts), str(msgload), "50", str(stop_s),
         str(stop_s), str(workers), "42"],
        check=True, capture_output=True, text=True,
    )
    return json.loads(out.stdout)


def _run_stage(stage: str, app_model: str, loss: float, app_options: dict,
               extra_counters: tuple = (), num_hosts: int = 10240,
               stop_s: int = 4, event_capacity: int = 1 << 15,
               extra_experimental: dict | None = None,
               windows_per_dispatch: int = 8):
    """Build, warm up (compile + bootstrap), then time the remaining sim
    span. Warm-up-committed events are subtracted so the reported rate and
    sim/wall ratio cover only the timed segment."""
    import jax

    from shadow_tpu.sim import build_simulation

    warmup_ns = 1_500_000_000
    n_servers = num_hosts // 8
    cfg = {
        "general": {"stop_time": stop_s, "seed": 7},
        "network": {"graph": {"type": "gml", "inline": (
            'graph [\n'
            '  node [ id 0 bandwidth_down "1 Gbit" bandwidth_up "1 Gbit" ]\n'
            f'  edge [ source 0 target 0 latency "10 ms" packet_loss {loss} ]\n'
            ']\n')}},
        # Pool capacity sized to the stage's in-flight population (timers +
        # packets in transit): oversizing it is pure waste — the per-window
        # pool sort is the dominant cost and scales with capacity.
        "experimental": {
            "event_capacity": event_capacity,
            "events_per_host_per_window": 16,
            "outbox_slots": 16,
            # ring/inbox capacities sized to the stage's queue depths:
            # every slot is a full [H, slots, P] write per update, so
            # oversizing is pure memory traffic
            "router_queue_slots": 16,
            "inbox_slots": 4,
            **(extra_experimental or {}),
        },
        "hosts": {
            "server": {"quantity": n_servers, "app_model": app_model,
                       "app_options": {"role": "server"}},
            "client": {"quantity": num_hosts - n_servers,
                       "app_model": app_model, "app_options": app_options},
        },
    }
    sim = build_simulation(cfg)
    # Bounded dispatch chunks: minutes-long single dispatches can crash the
    # accelerator runtime's watchdog at this scale, but each dispatch costs
    # ~8 ms of tunnel overhead (profiled), so size them as large as safe.
    sim.run(until=warmup_ns, windows_per_dispatch=windows_per_dispatch)
    jax.block_until_ready(sim.state.pool.time)
    warm_events = sim.counters()["events_committed"]
    t0 = time.perf_counter()
    sim.run(windows_per_dispatch=windows_per_dispatch)
    jax.block_until_ready(sim.state.pool.time)
    wall = time.perf_counter() - t0
    c = sim.counters()
    timed_events = c["events_committed"] - warm_events
    timed_sim_s = stop_s - warmup_ns / 1e9
    out = {
        "stage": stage,
        "hosts": num_hosts,
        "events_per_sec": round(timed_events / wall, 1),
        "packets_delivered": c["packets_delivered"],
        "sim_sec_per_wall_sec": round(timed_sim_s / wall, 2),
        # must stay 0 or the measurement dropped work
        "pool_overflow_dropped": c["pool_overflow_dropped"],
    }
    for k in extra_counters:
        out[k] = c[k]
    return out


def stage_udp_flood(num_hosts: int = 10240, stop_s: int = 4):
    """BASELINE staged config 2: 10k-host UDP flood through the full device
    network stack (NIC token buckets, CoDel router, UDP sockets)."""
    # Shapes tuned from the on-chip profile (tools/profile_flood.py): the
    # extraction/merge sorts carry C + H*(K+1) rows (+ H*(O+B) box rows in
    # the merge) and are ~60% of device time — K/O/C are sized to the
    # workload's Poisson tails, no further.
    return _run_stage(
        "udp_flood_10k", "udp_flood", 0.001,
        {"interval": "20 ms", "size": 1024, "runtime": stop_s - 1},
        # 1 << 14 pool capacity measurably overflows (1.5k drops); 1 << 15
        # does not
        num_hosts=num_hosts, stop_s=stop_s, event_capacity=1 << 15,
        extra_experimental={"events_per_host_per_window": 12,
                            "outbox_slots": 8},
        windows_per_dispatch=32,
    )


def stage_tcp_bulk(num_hosts: int = 10240, stop_s: int = 4):
    """BASELINE staged config 3: 10k-host TCP bulk transfer (vmap'd
    handshake + seq/ack + Reno congestion state machines)."""
    return _run_stage(
        "tcp_bulk_10k", "tcp_bulk", 0.0005, {"total": "64 KiB"},
        extra_counters=("bytes_delivered",),
        # in-flight population ~25 events/client (cwnd segments + ACKs +
        # pump/timer events): 1 << 16 measurably overflows, 1 << 18 does not
        num_hosts=num_hosts, stop_s=stop_s, event_capacity=1 << 18,
        # TCP self-events (timers + pumps) need more inbox headroom than
        # the UDP stage; the TCP handler suite's worst-case emission count
        # per event is 28 (engine probe), so the outbox must cover it —
        # O=16 fails the build-time probe (this is what blocked the r2
        # stage-3 recording)
        extra_experimental={"inbox_slots": 8, "outbox_slots": 32},
    )


def stage_phold_100k(stop_s: int = 10):
    """BASELINE staged configs 4-5 shape probe: 100k hosts on ONE chip
    (matrix fast path). msgload 2 → 20M+ committed events. SHORT dispatch
    chunks: at this scale a 64-window dispatch runs long enough to trip
    the accelerator runtime's watchdog and crash the worker."""
    num_hosts, msgload = 100_000, 2
    events, wall, sim_per_wall = device_phold(
        num_hosts, msgload, stop_s, windows_per_dispatch=4
    )
    base = cpp_phold_baseline(num_hosts, msgload, stop_s)
    rate = events / wall if wall > 0 else 0.0
    return {
        "stage": "phold_100k",
        "hosts": num_hosts,
        "events_per_sec": round(rate, 1),
        "sim_sec_per_wall_sec": round(sim_per_wall, 2),
        "vs_baseline": round(rate / (base["events_per_sec"] or 1.0), 3),
    }


def stage_udp_flood_100k(stop_s: int = 3):
    """100k hosts through the full device network stack on one chip."""
    return _run_stage(
        "udp_flood_100k", "udp_flood", 0.001,
        {"interval": "40 ms", "size": 1024, "runtime": stop_s - 1},
        num_hosts=100_352,  # 98 * 1024: divisible for future mesh splits
        stop_s=stop_s, event_capacity=1 << 18,
    )


def main():
    import sys

    if "--stages" in sys.argv:
        # staged measurement configs (BASELINE.md 2-3); one JSON line each
        print(json.dumps(stage_udp_flood()))
        print(json.dumps(stage_tcp_bulk()))
        return
    if "--stages-100k" in sys.argv:
        # BASELINE configs 4-5 SHAPE at one-chip scale (VERDICT r3 #3)
        print(json.dumps(stage_phold_100k()))
        print(json.dumps(stage_udp_flood_100k()))
        return

    num_hosts, msgload, stop_s = 16384, 8, 10
    dev_events, dev_wall, sim_per_wall = device_phold(num_hosts, msgload, stop_s)
    dev_rate = dev_events / dev_wall if dev_wall > 0 else 0.0

    base = cpp_phold_baseline(num_hosts, msgload, stop_s)
    base_rate = base["events_per_sec"] or 1.0

    print(
        json.dumps(
            {
                "metric": "phold_committed_events_per_sec_per_chip",
                "value": round(dev_rate, 1),
                "unit": "events/sec",
                "vs_baseline": round(dev_rate / base_rate, 3),
                "detail": {
                    "hosts": num_hosts,
                    "msgload": msgload,
                    "sim_seconds": stop_s,
                    "device_events": int(dev_events),
                    "device_wall_s": round(dev_wall, 3),
                    "sim_sec_per_wall_sec": round(sim_per_wall, 2),
                    "baseline": base,
                },
            }
        )
    )


if __name__ == "__main__":
    main()
