"""Benchmark: on-device PHOLD throughput vs a CPU sequential-DES baseline.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The workload is the PHOLD PDES canary (reference src/test/phold/phold.yaml:
peers over a 50ms self-loop link exchanging random-destination messages),
scaled up. `value` is committed events/sec on the device for the full fused
run (one XLA while_loop program). `vs_baseline` is the speedup over a pure
sequential heapq discrete-event loop executing the same logical workload on
this machine's CPU — the same single-threaded scheduler structure the
reference's per-worker event loop uses (scheduler_policy_host_single.c).
"""

from __future__ import annotations

import heapq
import json
import random
import time


def device_phold(num_hosts: int, msgload: int, stop_s: int):
    import jax

    from shadow_tpu.core import simtime
    from shadow_tpu.flagship import build_phold_flagship

    sim = build_phold_flagship(
        num_hosts, msgload=msgload, stop_s=stop_s, runtime_s=stop_s
    )
    # Warm-up compile (cached), then timed run.
    sim.run(until=int(0.2 * simtime.NS_PER_SEC))
    jax.block_until_ready(sim.state.pool.time)
    t0 = time.perf_counter()
    sim.run()
    jax.block_until_ready(sim.state.pool.time)
    wall = time.perf_counter() - t0
    c = sim.counters()
    return c["events_committed"], wall, stop_s / wall


def cpu_phold_baseline(num_hosts: int, msgload: int, stop_s: int):
    """Sequential heapq DES of the same workload (python stands in for the
    reference's C event loop; ratio is reported honestly as such)."""
    latency = 50_000_000
    stop = stop_s * 1_000_000_000
    start = 1_000_000_000
    rng = random.Random(42)
    heap = []
    seqs = [0] * num_hosts
    for h in range(num_hosts):
        for _ in range(msgload):
            heapq.heappush(heap, (start, h, h, seqs[h]))
            seqs[h] += 1
    committed = 0
    t0 = time.perf_counter()
    while heap and heap[0][0] < stop:
        t, dst, src, seq = heapq.heappop(heap)
        committed += 1
        nd = rng.randrange(num_hosts - 1)
        if nd >= dst:
            nd += 1
        heapq.heappush(heap, (t + latency, nd, dst, seqs[dst]))
        seqs[dst] += 1
    wall = time.perf_counter() - t0
    return committed, wall


def main():
    num_hosts, msgload, stop_s = 8192, 8, 10
    dev_events, dev_wall, sim_per_wall = device_phold(num_hosts, msgload, stop_s)
    dev_rate = dev_events / dev_wall if dev_wall > 0 else 0.0

    # Baseline on a smaller slice of simulated time, extrapolated by rate.
    base_events, base_wall = cpu_phold_baseline(num_hosts, msgload, 2)
    base_rate = base_events / base_wall if base_wall > 0 else 1.0

    print(
        json.dumps(
            {
                "metric": "phold_committed_events_per_sec_per_chip",
                "value": round(dev_rate, 1),
                "unit": "events/sec",
                "vs_baseline": round(dev_rate / base_rate, 3),
                "detail": {
                    "hosts": num_hosts,
                    "msgload": msgload,
                    "sim_seconds": stop_s,
                    "device_events": int(dev_events),
                    "device_wall_s": round(dev_wall, 3),
                    "sim_sec_per_wall_sec": round(sim_per_wall, 2),
                    "cpu_heapq_events_per_sec": round(base_rate, 1),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
