// Simulator <-> managed-process IPC channel (shadow_tpu native plane).
//
// Role parity: the reference's shm message channel + spinning binary
// semaphores (src/lib/shim/ipc.h, binary_spinning_sem.h:13-50) and shm
// block registry (src/main/shmem/). Design differences, both deliberate:
//   * One channel per managed thread lives in its OWN shm file (created by
//     the driver, name passed via env) — no global buddy allocator needed,
//     because the only cross-process allocations ARE the channels.
//   * The data plane rides inline in the channel (DATA_MAX chunks) instead
//     of a remote memory manager reading /proc/pid/mem: syscall buffer
//     contents are memcpy'd by the shim itself. Bounded, simple, and the
//     copy cost is far below the simulated network's per-packet work.
//   * Semaphores are POSIX process-shared sems with a bounded user-space
//     spin before sem_wait (same hybrid the reference built by hand).
//
// Layout is pinned with static_asserts so the Python driver can address
// fields by fixed offsets (ctypes) without a bindings generator.

#pragma once

#include <semaphore.h>
#include <stddef.h>
#include <stdint.h>

namespace shadow_tpu {

constexpr uint32_t IPC_MAGIC = 0x53545031;  // "STP1"
constexpr uint32_t IPC_DATA_MAX = 1 << 16;  // inline data plane per message

// message types
enum MsgType : int32_t {
  MSG_NONE = 0,
  MSG_HELLO = 1,     // shim -> driver: managed process is alive (ret = pid)
  MSG_SYSCALL = 2,   // shim -> driver: sysno + args (+ inline data for writes)
  MSG_RESULT = 3,    // driver -> shim: ret (+ inline data for reads)
  MSG_DO_NATIVE = 4, // driver -> shim: run the syscall natively, in-process
  MSG_STOP = 5,      // driver -> shim: tear the process down
};

// pseudo-syscall numbers for calls that have no raw-syscall form or need
// simulator-side name resolution (reference analog: the custom
// shadow_hostname_to_addr_ipv4 syscall used by getaddrinfo interposition)
enum PseudoSys : int64_t {
  PSYS_RESOLVE_NAME = -100,  // data = hostname; ret = ipv4 (host order)
  PSYS_YIELD = -101,         // report-in; lets the driver advance sim time
  PSYS_GETHOSTNAME = -102,   // reply data = this host's simulated name
  // threads / processes (reference analogs: thread_preload.c:358-400 clone
  // bootstrap, process.c:460-531 fork/exec)
  PSYS_THREAD_NEW = -103,   // reply data = new thread's channel shm name
  PSYS_THREAD_EXIT = -104,  // this thread is done (no reply expected data)
  PSYS_FORK = -105,         // reply data = child process's channel shm name
  PSYS_EXEC = -106,  // data = argv NUL-list "\0" envp NUL-list; the driver
                     // RESPAWNS the image as a fresh managed process that
                     // keeps this process's virtual identity (fds, pid
                     // bookkeeping, parent/waitpid linkage) and the caller
                     // _exits — native execve under the inherited seccomp
                     // filter is unsurvivable (no SIGSYS handler until the
                     // new shim constructor, but glibc startup already
                     // hits trapped syscalls)
  // futex-class blocking (reference: futex.c:19-30, syscall/futex.c); the
  // shim reads the futex word itself (same address space), the driver only
  // parks/wakes threads keyed by (process, uaddr)
  PSYS_FUTEX_WAIT = -107,  // args: uaddr, timeout_ns (-1 none); ret 0/ETIMEDOUT
  PSYS_FUTEX_WAKE = -108,  // args: uaddr, n; ret = number woken
  PSYS_WAITPID = -109,     // args: pid (-1 any); ret = pid, data = i32 status
  PSYS_FSTAT = -111,       // args: fd; ret = FD_KIND_* of the managed fd
  PSYS_FD_LIST = -112,     // ret = count; data = i32[] open managed fds
  // handler-return notification: restores the pre-delivery signal mask
  // (the delivery auto-blocked the signal + sa_mask, Linux semantics)
  PSYS_SIG_RETURN = -110,
};

#pragma pack(push, 8)
struct Channel {
  uint32_t magic;        // 0
  int32_t shim_pid;      // 4
  sem_t to_driver;       // 8   (sem_t = 32 bytes on x86-64 glibc)
  sem_t to_shim;         // 40
  int32_t type;          // 72
  int32_t pad0;          // 76
  int64_t sysno;         // 80
  int64_t args[6];       // 88
  int64_t ret;           // 136
  int64_t sim_time_ns;   // 144  driver stamps sim clock on every response
  // Signal delivery plane (reference analog: syscall/signal.c emulation +
  // process_continue signal checks): the driver piggybacks at most one
  // pending virtual signal on each reply; the shim invokes the app's
  // registered handler (address recorded via the interposed sigaction)
  // before returning from the syscall wrapper.
  int32_t sig_no;        // 152  0 = none
  int32_t sig_flags;     // 156  bit 0: SA_SIGINFO-style 3-arg handler
  uint64_t sig_handler;  // 160  app handler address (in its own space)
  int32_t data_len;      // 168
  int32_t pad1;          // 172
  uint8_t data[IPC_DATA_MAX];  // 176
};
#pragma pack(pop)

static_assert(sizeof(sem_t) == 32, "expected glibc x86-64 sem_t");
static_assert(offsetof(Channel, to_driver) == 8, "layout pinned for ctypes");
static_assert(offsetof(Channel, type) == 72, "layout pinned for ctypes");
static_assert(offsetof(Channel, sysno) == 80, "layout pinned for ctypes");
static_assert(offsetof(Channel, args) == 88, "layout pinned for ctypes");
static_assert(offsetof(Channel, ret) == 136, "layout pinned for ctypes");
static_assert(offsetof(Channel, sim_time_ns) == 144, "layout pinned");
static_assert(offsetof(Channel, sig_no) == 152, "layout pinned");
static_assert(offsetof(Channel, sig_handler) == 160, "layout pinned");
static_assert(offsetof(Channel, data_len) == 168, "layout pinned");
static_assert(offsetof(Channel, data) == 176, "layout pinned for ctypes");

// Bounded spin before parking on the semaphore: the driver usually replies
// within a few microseconds; spinning avoids a futex round trip
// (binary_spinning_sem.h analog). The spin count is tuned by env
// SHADOW_TPU_SPIN (0 disables).
inline void sem_wait_spinning(sem_t* sem, long spin_max) {
  for (long i = 0; i < spin_max; ++i) {
    if (sem_trywait(sem) == 0) return;
#if defined(__x86_64__)
    __builtin_ia32_pause();
#endif
  }
  while (sem_wait(sem) != 0) {
  }
}

// env var names (driver sets them in the child environment)
constexpr const char* ENV_SHM = "SHADOW_TPU_SHM";     // shm file name
constexpr const char* ENV_SPIN = "SHADOW_TPU_SPIN";   // spin iterations
constexpr const char* ENV_DEBUG = "SHADOW_TPU_SHIM_DEBUG";
constexpr const char* ENV_SECCOMP = "SHADOW_TPU_SECCOMP";  // "0" disables
constexpr const char* ENV_VDSO = "SHADOW_TPU_VDSO";        // "0" disables patch
// "1" prefixes each stdout/stderr line with the sim clock (reference
// analog: shim_logger.c sim-time stamping inside the managed process)
constexpr const char* ENV_LOG_STAMP = "SHADOW_TPU_LOG_STAMP";

// emulated fd space starts here; lower fds (stdio, real files the process
// opens itself) stay native. The reference instead virtualizes the entire
// fd table (descriptor_table.rs); partitioning keeps real-file IO native
// with zero syscall traffic.
constexpr int FD_BASE = 1000;

// fd kinds reported by PSYS_FSTAT (shim builds struct stat from these)
enum {
  FD_KIND_OTHER = 0,
  FD_KIND_SOCKET = 1,
  FD_KIND_PIPE = 2,
  FD_KIND_EVENTFD = 3,
  FD_KIND_TIMERFD = 4,
  FD_KIND_EPOLL = 5,
};

}  // namespace shadow_tpu
