// Reference-replica PHOLD baseline: the Shadow CPU scheduler's hot path,
// re-implemented faithfully in C++ so the TPU engine has a real
// reference-class number to beat on this machine.
//
// Why a replica and not the reference itself: this image has no cargo/rustc
// (Shadow's config/worker layer is a Rust staticlib), no glib, no igraph,
// and zero network egress to fetch them — the reference cannot build here.
// This program replicates the exact structures its PHOLD benchmark
// exercises (citations into /root/reference):
//   * per-host event priority queues, each behind a lock
//     (src/main/core/scheduler/scheduler_policy_host_single.c:18-54)
//   * hosts sharded round-robin across worker pthreads
//     (src/main/core/scheduler/scheduler.c:329-353)
//   * conservative windows bounded by the min path latency, with a
//     barrier + min-next-event-time reduction between rounds
//     (src/main/core/controller.c:390-422, core/worker.c:332-363)
//   * deterministic total order (time, dst, src, seq)
//     (src/main/core/work/event.c:109-152)
//   * cross-host sends: reliability roll, latency add, push to the
//     destination host's locked queue (src/main/core/worker.c:517-576)
//   * per-host seeded rand_r streams (src/main/utility/random.c:15-51)
// The PHOLD workload itself mirrors src/test/phold: msgload initial
// messages per host, each handled event forwards to a uniform-random
// destination at now + latency while now < stop_send.
//
// Usage: phold_baseline <hosts> <msgload> <latency_ms> <runtime_s> <stop_s>
//                       <workers> <seed>
// Prints one JSON line with committed events, wall seconds, events/sec and
// simulated-seconds per wall-second.

#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <queue>
#include <vector>

namespace {

struct Event {
  int64_t time;
  int32_t dst;
  int32_t src;
  int64_t seq;
};

// event.c:109-152 total order: time, then dst, then src, then sequence
struct EventGreater {
  bool operator()(const Event& a, const Event& b) const {
    if (a.time != b.time) return a.time > b.time;
    if (a.dst != b.dst) return a.dst > b.dst;
    if (a.src != b.src) return a.src > b.src;
    return a.seq > b.seq;
  }
};

struct Host {
  pthread_mutex_t lock;
  std::priority_queue<Event, std::vector<Event>, EventGreater> q;
  unsigned int rng;    // rand_r state (random.c analog)
  int64_t seq_next;
  int64_t committed;
};

struct Shared {
  std::vector<Host>* hosts;
  int64_t barrier_end;     // current window end (exclusive)
  int64_t stop_send;
  int64_t stop;
  int64_t latency;
  int nworkers;
  pthread_barrier_t round_barrier;
  std::vector<int64_t>* min_next;  // per-worker min next event time
  std::atomic<bool> done;
};

constexpr int64_t NEVER = INT64_MAX;

struct WorkerArg {
  Shared* sh;
  int id;
};

void* worker_main(void* vp) {
  WorkerArg* wa = (WorkerArg*)vp;
  Shared* sh = wa->sh;
  std::vector<Host>& hosts = *sh->hosts;
  const int H = (int)hosts.size();
  const int W = sh->nworkers;
  const int id = wa->id;

  while (true) {
    pthread_barrier_wait(&sh->round_barrier);  // round begin
    if (sh->done.load(std::memory_order_relaxed)) return nullptr;
    const int64_t wend = sh->barrier_end;
    int64_t my_min = NEVER;
    // _scheduler_runEventsWorkerTaskFn analog: each worker drains its
    // hosts' queues up to the barrier (scheduler.c:77-94)
    for (int h = id; h < H; h += W) {
      Host& host = hosts[h];
      while (true) {
        pthread_mutex_lock(&host.lock);
        if (host.q.empty() || host.q.top().time >= wend) {
          if (!host.q.empty())
            my_min = std::min(my_min, host.q.top().time);
          pthread_mutex_unlock(&host.lock);
          break;
        }
        Event ev = host.q.top();
        host.q.pop();
        pthread_mutex_unlock(&host.lock);
        host.committed++;
        if (ev.time < sh->stop_send) {
          // forward to a uniform random other host (test_phold.c analog)
          unsigned int r = rand_r(&host.rng);
          int dst = (int)((uint64_t)r * (uint64_t)(H - 1) / ((uint64_t)RAND_MAX + 1));
          if (dst >= h) dst++;
          // reliability roll placeholder (loss 0 in the PHOLD graph, but
          // the reference still rolls: worker.c:539-545)
          (void)rand_r(&host.rng);
          Event ne{ev.time + sh->latency, dst, h, 0};
          Host& dh = hosts[dst];
          // scheduler_push analog: lock the DESTINATION queue
          pthread_mutex_lock(&dh.lock);
          ne.seq = dh.seq_next++;
          dh.q.push(ne);
          pthread_mutex_unlock(&dh.lock);
          // The PUSHER records the new event's time: the destination's
          // owner may already have swept past an empty queue this round,
          // so relying on per-queue observation alone could reduce to
          // NEVER with live events still queued (worker.c:332-363 has the
          // same push-side min update).
          my_min = std::min(my_min, ne.time);
        }
      }
    }
    (*sh->min_next)[id] = my_min;
    pthread_barrier_wait(&sh->round_barrier);  // round end
  }
}

}  // namespace

int main(int argc, char** argv) {
  const int H = argc > 1 ? atoi(argv[1]) : 1024;
  const int msgload = argc > 2 ? atoi(argv[2]) : 2;
  const int64_t latency_ms = argc > 3 ? atoll(argv[3]) : 50;
  const int64_t runtime_s = argc > 4 ? atoll(argv[4]) : 8;
  const int64_t stop_s = argc > 5 ? atoll(argv[5]) : 10;
  int nworkers = argc > 6 ? atoi(argv[6]) : 0;
  const unsigned seed = argc > 7 ? (unsigned)atoi(argv[7]) : 42;
  if (nworkers <= 0) {
    long n = sysconf(_SC_NPROCESSORS_ONLN);
    nworkers = n > 0 ? (int)n : 1;
  }
  if (nworkers > H) nworkers = H;

  const int64_t NS = 1000000000LL;
  const int64_t latency = latency_ms * 1000000LL;
  const int64_t start = NS;  // processes start at 1s like the flagship
  const int64_t stop_send = start + runtime_s * NS;
  const int64_t stop = stop_s * NS;

  std::vector<Host> hosts(H);
  for (int h = 0; h < H; h++) {
    pthread_mutex_init(&hosts[h].lock, nullptr);
    hosts[h].rng = seed * 2654435761u + (unsigned)h;  // per-host stream
    hosts[h].seq_next = 0;
    hosts[h].committed = 0;
    for (int m = 0; m < msgload; m++)
      hosts[h].q.push(Event{start, h, h, hosts[h].seq_next++});
  }

  Shared sh;
  sh.hosts = &hosts;
  sh.stop_send = stop_send;
  sh.stop = stop;
  sh.latency = latency;
  sh.nworkers = nworkers;
  sh.done.store(false);
  std::vector<int64_t> min_next(nworkers, NEVER);
  sh.min_next = &min_next;
  pthread_barrier_init(&sh.round_barrier, nullptr, nworkers + 1);

  std::vector<pthread_t> tids(nworkers);
  std::vector<WorkerArg> args(nworkers);
  for (int w = 0; w < nworkers; w++) {
    args[w] = WorkerArg{&sh, w};
    pthread_create(&tids[w], nullptr, worker_main, &args[w]);
  }

  auto t0 = std::chrono::steady_clock::now();
  int64_t window_start = start;
  int64_t windows = 0;
  // controller_managerFinishedCurrentRound analog (controller.c:390-422):
  // window = [minNextEventTime, minNextEventTime + runahead)
  while (window_start < stop) {
    sh.barrier_end = std::min(window_start + latency, stop);
    pthread_barrier_wait(&sh.round_barrier);  // release workers
    pthread_barrier_wait(&sh.round_barrier);  // wait for round end
    windows++;
    int64_t mn = NEVER;
    for (int w = 0; w < nworkers; w++) mn = std::min(mn, min_next[w]);
    if (mn == NEVER) break;
    window_start = mn;
  }
  sh.done.store(true);
  pthread_barrier_wait(&sh.round_barrier);
  for (int w = 0; w < nworkers; w++) pthread_join(tids[w], nullptr);
  auto t1 = std::chrono::steady_clock::now();

  int64_t committed = 0;
  for (int h = 0; h < H; h++) committed += hosts[h].committed;
  double wall = std::chrono::duration<double>(t1 - t0).count();
  double sim_s = (double)(stop - start) / 1e9;
  printf(
      "{\"baseline\": \"shadow-replica-cpp\", \"hosts\": %d, "
      "\"msgload\": %d, \"workers\": %d, \"windows\": %lld, "
      "\"events_committed\": %lld, \"wall_s\": %.3f, "
      "\"events_per_sec\": %.0f, \"sim_per_wall\": %.3f}\n",
      H, msgload, nworkers, (long long)windows, (long long)committed, wall,
      (double)committed / wall, sim_s / wall);
  return 0;
}
