// shadow_tpu managed-process shim (LD_PRELOAD).
//
// Role parity with the reference's shim plane (src/lib/shim/shim.c,
// preload_syscall.c, preload_libraries.c): co-opts a real Linux binary into
// the simulation by interposing at the libc API, relaying network/time
// syscalls over a shared-memory channel to the simulator driver, which
// executes them against the device-stepped network and the simulated clock.
//
// Interposition model (differences from the reference, all deliberate):
//   * libc-symbol interposition is the fast path; a seccomp/SIGSYS
//     backstop (reference analog: shim.c:399-463 seccomp filter + SIGSYS
//     trampoline) catches raw syscall instructions that bypass the PLT —
//     statically-linked binaries, libc internals, inline `syscall(2)`.
//     The BPF filter traps only the emulated syscall numbers and allows
//     everything issued from the shim's own gate function, so shim-internal
//     native calls never pay the signal round trip. Disable with
//     SHADOW_TPU_SECCOMP=0.
//     exec is handled as DRIVER RESPAWN: execve relays PSYS_EXEC and the
//     driver re-spawns the process image on a fresh channel with virtual
//     identity preserved (fds >= FD_BASE, host, pid) — the exec'd image
//     loads its own shim copy, so the filter + handler are re-installed
//     cleanly. fork relays PSYS_FORK onto a pre-created child channel.
//     KNOWN LIMIT: statically-linked binaries never load the shim at all
//     (no LD_PRELOAD), so nothing installs the filter — they run
//     UNSIMULATED. The reference covers them with ptrace
//     (thread_ptrace.c); this plane does not.
//     KNOWN LIMIT: vDSO-backed calls (clock_gettime/gettimeofday/time)
//     never enter the kernel, so seccomp cannot see them. shim_patch_vdso
//     neutralizes this at init by rewriting the vDSO entry points to real
//     `syscall` instructions (written through /proc/self/mem, which
//     bypasses page protections), so they fall into the trapped path. If
//     the patch fails (exotic kernel/vDSO layout) the gap REMAINS for
//     statically-linked binaries whose libc calls the vDSO directly —
//     the failure is logged loudly; dynamically-linked binaries are still
//     covered by libc-symbol interposition either way.
//   * fd space is PARTITIONED: emulated sockets/epolls live at
//     fd >= FD_BASE; anything below is passed through natively. Real-file
//     IO therefore costs zero simulator traffic (the reference instead
//     virtualizes the whole fd table and dups real files into it).
//   * Buffers are memcpy'd through the channel's inline data plane
//     (bounded; large transfers chunk at DATA_MAX per call) rather than
//     read remotely out of plugin memory by the simulator.
//
// Thread model: each thread gets its OWN channel (pthread_create relays
// PSYS_THREAD_NEW; the driver hands back a fresh channel path). The driver
// enforces one-runnable-thread-per-process between syscalls, which is what
// keeps multithreaded apps deterministic (docs/multiproc_design.md).

#include "../common/ipc.h"

#include <arpa/inet.h>
#include <atomic>
#include <dlfcn.h>
#include <elf.h>
#include <errno.h>
#include <fcntl.h>
#include <sys/auxv.h>
#include <linux/audit.h>
#include <linux/filter.h>
#include <linux/seccomp.h>
#include <netdb.h>
#include <signal.h>
#include <sys/prctl.h>
#include <ucontext.h>
#include <netinet/in.h>
#include <poll.h>
#include <sys/un.h>
#include <sched.h>
#include <pthread.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <climits>
#include <ifaddrs.h>
#include <net/if.h>
#include <dirent.h>
#include <sys/mman.h>
#include <sys/resource.h>
#include <sys/stat.h>
#include <sys/random.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <sys/timerfd.h>
#include <sys/wait.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <sys/utsname.h>
#include <time.h>
#include <unistd.h>

using namespace shadow_tpu;

// ---------------------------------------------------------------------------
// the syscall gate: the ONE code location the seccomp filter whitelists by
// instruction pointer (reference analog: the shim's designated trampoline
// that the BPF allows, shim.c seccomp install). Every native syscall the
// shim itself makes goes through here, so shim-internal work never traps.
// Raw kernel convention: returns -errno on failure.
// ---------------------------------------------------------------------------

extern "C" __attribute__((noinline, aligned(256), section(".shim_gate")))
long shim_gate_syscall(long n, long a0, long a1, long a2, long a3, long a4,
                       long a5) {
#if defined(__x86_64__)
  long ret;
  register long r10 __asm__("r10") = a3;
  register long r8 __asm__("r8") = a4;
  register long r9 __asm__("r9") = a5;
  __asm__ volatile("syscall"
                   : "=a"(ret)
                   : "0"(n), "D"(a0), "S"(a1), "d"(a2), "r"(r10), "r"(r8),
                     "r"(r9)
                   : "rcx", "r11", "memory");
  return ret;
#else
  long r = ::syscall(n, a0, a1, a2, a3, a4, a5);
  return r < 0 ? -(long)errno : r;
#endif
}

namespace {

// size of the IP window the BPF whitelists around shim_gate_syscall
constexpr uint32_t GATE_WINDOW = 256;

// libc-convention wrapper over the gate: errno + -1 on failure. Variadic
// like syscall(2) so pointer args pass without explicit casts.
template <typename... Args>
long sys_native(long n, Args... args) {
  long vals[] = {(long)(args)..., 0, 0, 0, 0, 0, 0};
  long r = shim_gate_syscall(n, vals[0], vals[1], vals[2], vals[3], vals[4],
                             vals[5]);
  if (r < 0 && r > -4096) {
    errno = (int)-r;
    return -1;
  }
  return r;
}

Channel* g_ch = nullptr;  // process-primary channel (thread 0's)
long g_spin = 8192;
int g_debug = 0;
// count of virtual-signal handler invocations on this thread (reply
// piggyback path) — lets composed mask-swapping waits (ppoll/epoll_pwait)
// report EINTR when a pending signal fires at the mask swap, as the
// kernel's atomic form would
thread_local uint64_t g_sig_handled = 0;
int g_log_stamp = 0;  // ENV_LOG_STAMP: sim-time prefix on stdout/stderr lines
// per-fd (stdout, stderr) at-beginning-of-line state for the stamper
bool g_at_bol[2] = {true, true};
// Never-cleared channel alias for sim-time reads: exit teardown nulls g_ch
// (shim_notify_exit) BEFORE stdio flushes its buffers, and those flushed
// lines still deserve stamps — the shm stays mapped for the process life.
Channel* g_stamp_ch = nullptr;
// Thread-local channel: every pthread_create'd thread gets its OWN shm
// channel from the driver (reference analog: per-thread IPC blocks,
// thread_preload.c:131-179). Threads without one (e.g. raw clone) share
// g_ch under a raw spinlock — NOT a pthread mutex, because pthread mutexes
// are interposed below and their contended path relays through ipc_call.
__thread Channel* t_ch = nullptr;
std::atomic_flag g_ch_lock = ATOMIC_FLAG_INIT;

inline Channel* cur_channel() { return t_ch ? t_ch : g_ch; }

void raw_lock(std::atomic_flag* f) {
  while (f->test_and_set(std::memory_order_acquire)) {
#if defined(__x86_64__)
    __builtin_ia32_pause();
#endif
  }
}
void raw_unlock(std::atomic_flag* f) { f->clear(std::memory_order_release); }

#define SHIM_LOG(...)                                 \
  do {                                                \
    if (g_debug) {                                    \
      fprintf(stderr, "[shadow-tpu-shim %d] ", getpid()); \
      fprintf(stderr, __VA_ARGS__);                   \
      fprintf(stderr, "\n");                          \
    }                                                 \
  } while (0)

bool is_managed_fd(int fd) { return g_ch != nullptr && fd >= FD_BASE; }

// Terminate WITHOUT the driver notification (raw exit_group): used by
// shim-internal teardown paths where the driver already knows (MSG_STOP,
// exec respawn) or where notifying would recurse.
[[noreturn]] void raw_exit(int status) {
  sys_native(SYS_exit_group, status);
  __builtin_unreachable();
}

void shim_install_seccomp();  // defined at the bottom (needs the wrappers)
void shim_patch_vdso();       // defined at the bottom
extern "C" void shim_install_tsc_trap();  // rdtsc virtualization (tsc.c)
void shim_notify_exit(int status, void*);  // defined with the thread plane

// One request/response round trip. data_in/data_in_len ride to the driver;
// the reply's inline data is copied to data_out (up to data_out_cap).
// Returns the driver's ret, with errno set for negative returns.
int64_t ipc_call(int64_t sysno, const int64_t args[6], const void* data_in,
                 uint32_t data_in_len, void* data_out, uint32_t data_out_cap,
                 uint32_t* data_out_len) {
  Channel* ch = cur_channel();
  if (!ch) {
    errno = ENOSYS;
    return -1;
  }
  // Every g_ch user — including the main thread — takes the spinlock:
  // a thread whose own channel failed to map (or a raw-clone thread)
  // falls back to g_ch and would otherwise race the main thread on it.
  const bool shared = (ch == g_ch);
  if (shared) raw_lock(&g_ch_lock);
  ch->type = MSG_SYSCALL;
  ch->sysno = sysno;
  for (int i = 0; i < 6; i++) ch->args[i] = args ? args[i] : 0;
  uint32_t n = data_in_len > IPC_DATA_MAX ? IPC_DATA_MAX : data_in_len;
  ch->data_len = (int32_t)n;
  if (n && data_in) memcpy(ch->data, data_in, n);
  sem_post(&ch->to_driver);
  sem_wait_spinning(&ch->to_shim, g_spin);

  int64_t ret = ch->ret;
  int32_t mtype = ch->type;
  int32_t sig_no = ch->sig_no;
  int32_t sig_flags = ch->sig_flags;
  uint64_t sig_handler = ch->sig_handler;
  uint32_t out_n = 0;
  if (data_out && ch->data_len > 0) {
    out_n = (uint32_t)ch->data_len;
    if (out_n > data_out_cap) out_n = data_out_cap;
    memcpy(data_out, ch->data, out_n);
  }
  if (data_out_len) *data_out_len = out_n;
  if (shared) raw_unlock(&g_ch_lock);

  if (mtype == MSG_STOP) {
    SHIM_LOG("driver requested stop");
    raw_exit((int)ret);
  }
  // Virtual signal piggybacked on the reply (driver-side signal.c analog):
  // invoke the app's registered handler here, at a syscall boundary — the
  // deterministic delivery point. The transaction above is complete, so
  // handler-made syscalls recurse safely through the channel.
  if (sig_no > 0 && sig_handler != 0) {
    SHIM_LOG("delivering virtual signal %d", sig_no);
    g_sig_handled++;  // ppoll/pselect compose: detect delivery-on-entry
    if (sig_flags & 1) {  // SA_SIGINFO-style handler
      siginfo_t si;
      memset(&si, 0, sizeof(si));
      si.si_signo = sig_no;
      ((void (*)(int, siginfo_t*, void*))sig_handler)(sig_no, &si, nullptr);
    } else {
      ((void (*)(int))sig_handler)(sig_no);
    }
    // handler done: restore the pre-delivery mask (driver auto-blocked the
    // signal + sa_mask for the handler's duration — Linux semantics). The
    // return reply may itself carry the NEXT now-unblocked pending signal.
    ipc_call(PSYS_SIG_RETURN, nullptr, nullptr, 0, nullptr, 0, nullptr);
  }
  if (mtype == MSG_DO_NATIVE) {
    return sys_native((long)sysno, args[0], args[1], args[2], args[3],
                      args[4], args[5]);
  }
  if (ret < 0) {
    errno = (int)-ret;
    return -1;
  }
  return ret;
}

int64_t ipc_call6(int64_t sysno, int64_t a0 = 0, int64_t a1 = 0,
                  int64_t a2 = 0, int64_t a3 = 0, int64_t a4 = 0,
                  int64_t a5 = 0) {
  int64_t args[6] = {a0, a1, a2, a3, a4, a5};
  return ipc_call(sysno, args, nullptr, 0, nullptr, 0, nullptr);
}

// Extract (ipv4 host-order, port host-order) from a sockaddr.
bool parse_inet(const struct sockaddr* addr, socklen_t len, uint32_t* ip,
                uint16_t* port) {
  if (!addr || len < (socklen_t)sizeof(struct sockaddr_in)) return false;
  if (addr->sa_family != AF_INET) return false;
  const struct sockaddr_in* sin = (const struct sockaddr_in*)addr;
  *ip = ntohl(sin->sin_addr.s_addr);
  *port = ntohs(sin->sin_port);
  return true;
}

void fill_inet(struct sockaddr* addr, socklen_t* alen, uint32_t ip,
               uint16_t port) {
  if (!addr || !alen) return;
  struct sockaddr_in sin;
  memset(&sin, 0, sizeof(sin));
  sin.sin_family = AF_INET;
  sin.sin_addr.s_addr = htonl(ip);
  sin.sin_port = htons(port);
  socklen_t n = *alen < (socklen_t)sizeof(sin) ? *alen : (socklen_t)sizeof(sin);
  memcpy(addr, &sin, n);
  *alen = (socklen_t)sizeof(sin);
}

__attribute__((constructor)) void shim_init() {
  const char* path = getenv(ENV_SHM);
  if (!path) return;  // not under the simulator; stay inert
  const char* spin = getenv(ENV_SPIN);
  if (spin) g_spin = atol(spin);
  g_debug = getenv(ENV_DEBUG) != nullptr;
  const char* stamp = getenv(ENV_LOG_STAMP);
  g_log_stamp = stamp && strcmp(stamp, "0") != 0;
  int fd = open(path, O_RDWR);
  if (fd < 0) {
    fprintf(stderr, "shadow-tpu-shim: cannot open %s: %s\n", path,
            strerror(errno));
    return;
  }
  void* p = (void*)sys_native(SYS_mmap, (long)nullptr, sizeof(Channel),
                              PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED || ((Channel*)p)->magic != IPC_MAGIC) {
    fprintf(stderr, "shadow-tpu-shim: bad channel mapping\n");
    return;
  }
  g_ch = (Channel*)p;
  g_stamp_ch = g_ch;
  t_ch = g_ch;  // the main thread owns the primary channel
  g_ch->shim_pid = getpid();
  SHIM_LOG("attached, channel=%s", path);
  // HELLO round trip: driver replies with the current sim time
  g_ch->type = MSG_HELLO;
  g_ch->ret = getpid();
  g_ch->data_len = 0;
  sem_post(&g_ch->to_driver);
  sem_wait_spinning(&g_ch->to_shim, g_spin);
  // deterministic process-done notification (fork children inherit this
  // registration and notify on their own channel)
  on_exit(shim_notify_exit, nullptr);
  const char* sec = getenv(ENV_SECCOMP);
  if (!sec || strcmp(sec, "0") != 0) {
    shim_patch_vdso();  // before the filter: time must reach the kernel
    shim_install_seccomp();
    shim_install_tsc_trap();  // raw rdtsc reads the virtual clock too
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// sockets
// ---------------------------------------------------------------------------

extern "C" {

int socket(int domain, int type, int protocol) {
  // AF_INET and AF_UNIX are simulated; everything else stays native
  if (!g_ch || (domain != AF_INET && domain != AF_UNIX))
    return (int)sys_native(SYS_socket, domain, type, protocol);
  return (int)ipc_call6(SYS_socket, domain, type, protocol);
}

// Extract a sockaddr_un path ('@' prefix encodes the abstract namespace).
// Returns the path length (0 on failure).
static size_t parse_unix_path(const struct sockaddr* addr, socklen_t len,
                              char* out, size_t cap) {
  if (!addr || addr->sa_family != AF_UNIX) return 0;
  const struct sockaddr_un* sun = (const struct sockaddr_un*)addr;
  size_t off = offsetof(struct sockaddr_un, sun_path);
  if ((size_t)len <= off) return 0;
  size_t plen = (size_t)len - off;
  if (plen > sizeof(sun->sun_path)) plen = sizeof(sun->sun_path);
  size_t n = 0;
  if (sun->sun_path[0] == '\0') {  // abstract namespace
    if (cap < 1) return 0;
    out[n++] = '@';
    for (size_t i = 1; i < plen && n < cap; i++) out[n++] = sun->sun_path[i];
  } else {
    for (size_t i = 0; i < plen && n < cap && sun->sun_path[i]; i++)
      out[n++] = sun->sun_path[i];
  }
  return n;
}

int socketpair(int domain, int type, int protocol, int sv[2]) {
  if (!g_ch || domain != AF_UNIX)
    return (int)sys_native(SYS_socketpair, domain, type, protocol,
                           (long)sv);
  int64_t args[6] = {domain, type, protocol, 0, 0, 0};
  int32_t out[2] = {0, 0};
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_socketpair, args, nullptr, 0, out, sizeof(out),
                       &out_len);
  if (r < 0) return -1;
  if (out_len >= 8 && sv) {
    sv[0] = out[0];
    sv[1] = out[1];
  }
  return 0;
}

int bind(int fd, const struct sockaddr* addr, socklen_t len) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_bind, fd, addr, len);
  if (addr && addr->sa_family == AF_UNIX) {
    char path[110];
    size_t n = parse_unix_path(addr, len, path, sizeof(path));
    if (!n) {
      errno = EINVAL;
      return -1;
    }
    int64_t args[6] = {fd, 0, 0, 1 /* AF_UNIX path in data */, 0, 0};
    return (int)ipc_call(SYS_bind, args, path, (uint32_t)n, nullptr, 0,
                         nullptr);
  }
  uint32_t ip = 0;
  uint16_t port = 0;
  if (!parse_inet(addr, len, &ip, &port)) {
    errno = EINVAL;
    return -1;
  }
  return (int)ipc_call6(SYS_bind, fd, ip, port);
}

int listen(int fd, int backlog) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_listen, fd, backlog);
  return (int)ipc_call6(SYS_listen, fd, backlog);
}

int connect(int fd, const struct sockaddr* addr, socklen_t len) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_connect, fd, addr, len);
  if (addr && addr->sa_family == AF_UNIX) {
    char path[110];
    size_t n = parse_unix_path(addr, len, path, sizeof(path));
    if (!n) {
      errno = EINVAL;
      return -1;
    }
    int64_t args[6] = {fd, 0, 0, 1, 0, 0};
    return (int)ipc_call(SYS_connect, args, path, (uint32_t)n, nullptr, 0,
                         nullptr);
  }
  uint32_t ip = 0;
  uint16_t port = 0;
  if (!parse_inet(addr, len, &ip, &port)) {
    errno = EINVAL;
    return -1;
  }
  return (int)ipc_call6(SYS_connect, fd, ip, port);
}

// ---------------------------------------------------------------------------
// virtual signals (reference: syscall/signal.c emulation). The driver owns
// disposition tables, pending queues and per-thread masks; handlers run at
// syscall boundaries via the reply's sig_* fields (see ipc_call). Only the
// classic app-level set is virtualized — SIGSYS stays native (the seccomp
// backstop owns it), as do the fatal fault signals.
// ---------------------------------------------------------------------------

static constexpr uint64_t VIRT_SIG_MASK =
    (1ULL << (SIGHUP - 1)) | (1ULL << (SIGINT - 1)) |
    (1ULL << (SIGQUIT - 1)) | (1ULL << (SIGUSR1 - 1)) |
    (1ULL << (SIGUSR2 - 1)) | (1ULL << (SIGPIPE - 1)) |
    (1ULL << (SIGALRM - 1)) | (1ULL << (SIGTERM - 1)) |
    (1ULL << (SIGCHLD - 1));

static bool is_virt_sig(int sig) {
  return sig >= 1 && sig <= 64 && ((VIRT_SIG_MASK >> (sig - 1)) & 1);
}

// ---------------------------------------------------------------------------
// rdtsc virtualization (reference analog: host/tsc.c:127). PR_SET_TSC
// makes every raw rdtsc/rdtscp in app code fault; the SIGSEGV handler
// decodes the two instruction forms and emulates them from the channel's
// last-stamped sim time (a plain memory read — async-signal-safe): a
// virtual 1 GHz TSC where 1 cycle == 1 sim-ns. App timing loops built on
// rdtsc therefore read DETERMINISTIC virtual time instead of the real
// machine's, like every other clock under the simulator. An app's own
// SIGSEGV handler (registered through our sigaction) chains for
// non-rdtsc faults.
// ---------------------------------------------------------------------------

struct sigaction g_app_segv;   // app's chained SIGSEGV disposition
bool g_app_segv_set = false;
bool g_tsc_trap_on = false;    // emulator installed (gates the intercepts)

void on_sigsegv_tsc(int sig, siginfo_t* info, void* vctx) {
#if defined(__x86_64__)
  ucontext_t* uc = (ucontext_t*)vctx;
  greg_t* g = uc->uc_mcontext.gregs;
  const uint8_t* ip = (const uint8_t*)g[REG_RIP];
  // PR_TSC faults arrive with si_code SI_KERNEL and RIP at the (mapped,
  // executable) rdtsc insn; genuine memory faults are SEGV_MAPERR/ACCERR
  // — gate on that BEFORE reading *ip, or a wild jump to an unmapped
  // address would re-fault inside this handler
  if (info->si_code == SI_KERNEL && ip && ip[0] == 0x0F &&
      (ip[1] == 0x31 || (ip[1] == 0x01 && ip[2] == 0xF9))) {
    Channel* c = cur_channel();
    uint64_t ns = c ? (uint64_t)c->sim_time_ns : 0;
    // The channel stamp only advances at syscalls, so a busy-wait
    // calibrated purely on rdtsc (no syscall in the loop) would read a
    // frozen clock and spin forever. Advance the emulated TSC by one
    // virtual cycle (1 ns) per read past the stamp — deterministic
    // (per-thread counter, one-thread-at-a-time scheduling), monotonic,
    // and a pure-rdtsc delay loop of N cycles now terminates after N
    // reads while staying pinned to sim time whenever syscalls stamp it.
    static thread_local uint64_t last_tsc_read = 0;
    if (ns <= last_tsc_read) ns = last_tsc_read + 1;
    last_tsc_read = ns;
    g[REG_RAX] = (greg_t)(ns & 0xFFFFFFFFu);
    g[REG_RDX] = (greg_t)(ns >> 32);
    if (ip[1] == 0x01) {       // rdtscp: also IA32_TSC_AUX -> ECX
      g[REG_RCX] = 0;
      g[REG_RIP] += 3;
    } else {
      g[REG_RIP] += 2;
    }
    return;
  }
#endif
  // not an rdtsc fault: hand to the app's handler if it has a callable
  // one; otherwise die like SIG_DFL (returning would restart the faulting
  // instruction forever — SIG_IGN on a hardware fault is DFL in Linux).
  // Chaining must preserve the app's registered sigaction SEMANTICS, not
  // just its function pointer: block its sa_mask (plus SIGSEGV itself
  // unless it asked SA_NODEFER) around the call, as the kernel would
  // have. sigprocmask is async-signal-safe; if the handler exits via
  // siglongjmp the mask restore below is skipped, but siglongjmp restores
  // the mask saved by sigsetjmp(.., 1) itself — the same contract the
  // handler relies on under the kernel. SA_ONSTACK delivery (Go/JVM
  // stack-overflow recovery on an altstack) is honored because OUR
  // handler is installed with SA_ONSTACK: the kernel already switched to
  // the app's sigaltstack before we run, so the chained call executes on
  // it too.
  if (g_app_segv_set) {
    sigset_t chain_mask = g_app_segv.sa_mask;
    if (!(g_app_segv.sa_flags & SA_NODEFER)) sigaddset(&chain_mask, SIGSEGV);
    sigset_t prev_mask;
    sigprocmask(SIG_BLOCK, &chain_mask, &prev_mask);
    if (g_app_segv.sa_flags & SA_SIGINFO) {
      g_app_segv.sa_sigaction(sig, info, vctx);
      sigprocmask(SIG_SETMASK, &prev_mask, nullptr);
      return;
    }
    if (g_app_segv.sa_handler != SIG_IGN &&
        g_app_segv.sa_handler != SIG_DFL) {
      g_app_segv.sa_handler(sig);
      sigprocmask(SIG_SETMASK, &prev_mask, nullptr);
      return;
    }
    sigprocmask(SIG_SETMASK, &prev_mask, nullptr);
  }
  signal(SIGSEGV, SIG_DFL);
  raise(SIGSEGV);
}

void shim_install_tsc_trap() {
#if defined(__x86_64__)
  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = on_sigsegv_tsc;
  // SA_ONSTACK: if the app registers a sigaltstack (Go, JVM, Rust guard
  // pages recover stack overflow there), genuine faults must be DELIVERED
  // on it — our handler sits in front of theirs, so it must carry the
  // flag or the chained handler would run on the overflowed stack and
  // double-fault. rdtsc emulation is a few words of stack either way.
  sa.sa_flags = SA_SIGINFO | SA_NODEFER | SA_ONSTACK;
  static auto real_sigaction =
      (int (*)(int, const struct sigaction*, struct sigaction*))dlsym(
          RTLD_NEXT, "sigaction");
  real_sigaction(SIGSEGV, &sa, nullptr);
  if (prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0) != 0) {
    SHIM_LOG("PR_SET_TSC unavailable: raw rdtsc stays unvirtualized");
  } else {
    g_tsc_trap_on = true;
  }
#endif
}

int sigaction(int sig, const struct sigaction* act, struct sigaction* old) {
  static auto real_sigaction =
      (int (*)(int, const struct sigaction*, struct sigaction*))dlsym(
          RTLD_NEXT, "sigaction");
  if (g_ch && sig == SIGSEGV && g_tsc_trap_on) {
    // keep the rdtsc trap installed; the app's handler chains for
    // genuine faults (on_sigsegv_tsc dispatches non-rdtsc hits to it).
    // Only when the trap is actually installed — otherwise the app's
    // registration must reach the kernel normally.
    if (old) *old = g_app_segv;
    if (act) {
      g_app_segv = *act;
      g_app_segv_set = true;
    }
    return 0;
  }
  if (!g_ch || !is_virt_sig(sig)) return real_sigaction(sig, act, old);
  int64_t handler = 0, flags = 0;
  uint64_t mask = 0;
  if (act) {
    handler = (act->sa_flags & SA_SIGINFO) ? (int64_t)act->sa_sigaction
                                           : (int64_t)act->sa_handler;
    flags = act->sa_flags;
    memcpy(&mask, &act->sa_mask, sizeof(mask));
  }
  int64_t args[6] = {sig, handler, flags, (int64_t)mask, act ? 1 : 0, 0};
  uint8_t out[16];
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_rt_sigaction, args, nullptr, 0, out, sizeof(out),
                       &out_len);
  if (r < 0) return -1;
  if (old && out_len >= 12) {
    memset(old, 0, sizeof(*old));
    uint64_t oh;
    uint32_t of;
    memcpy(&oh, out, 8);
    memcpy(&of, out + 8, 4);
    old->sa_flags = (int)of;
    if (of & SA_SIGINFO)
      old->sa_sigaction = (void (*)(int, siginfo_t*, void*))oh;
    else
      old->sa_handler = (void (*)(int))oh;
  }
  return 0;
}

sighandler_t signal(int sig, sighandler_t h) {
  static auto real_signal =
      (sighandler_t(*)(int, sighandler_t))dlsym(RTLD_NEXT, "signal");
  if (!g_ch || !is_virt_sig(sig)) return real_signal(sig, h);
  struct sigaction act, old;
  memset(&act, 0, sizeof(act));
  act.sa_handler = h;
  act.sa_flags = SA_RESTART;
  if (sigaction(sig, &act, &old) != 0) return SIG_ERR;
  return old.sa_handler;
}

int sigprocmask(int how, const sigset_t* set, sigset_t* old) {
  static auto real_sigprocmask =
      (int (*)(int, const sigset_t*, sigset_t*))dlsym(RTLD_NEXT,
                                                      "sigprocmask");
  if (!g_ch) return real_sigprocmask(how, set, old);
  // native first, with the virtualized signals removed (they are never
  // delivered natively; the driver owns their mask)
  sigset_t nset;
  sigset_t nold;
  sigemptyset(&nold);
  if (set) {
    nset = *set;
    for (int s = 1; s <= 64; s++)
      if (is_virt_sig(s)) sigdelset(&nset, s);
  }
  if (real_sigprocmask(how, set ? &nset : nullptr, &nold) != 0) return -1;
  uint64_t vm = 0;
  if (set) {
    memcpy(&vm, set, sizeof(vm));
    vm &= VIRT_SIG_MASK;
  }
  // how: 0 block / 1 unblock / 2 setmask / 3 query-only
  int64_t vhow = set ? (int64_t)how : 3;
  int64_t args[6] = {vhow, (int64_t)vm, 0, 0, 0, 0};
  uint8_t out[8];
  uint32_t out_len = 0;
  int64_t r =
      ipc_call(SYS_rt_sigprocmask, args, nullptr, 0, out, sizeof(out),
               &out_len);
  if (old) {
    uint64_t om = 0;
    memcpy(&om, &nold, sizeof(om));
    om &= ~VIRT_SIG_MASK;
    uint64_t vold = 0;
    if (r >= 0 && out_len >= 8) memcpy(&vold, out, 8);
    om |= (vold & VIRT_SIG_MASK);
    memset(old, 0, sizeof(*old));
    memcpy(old, &om, sizeof(om));
  }
  return 0;
}

int pthread_sigmask(int how, const sigset_t* set, sigset_t* old) {
  if (!g_ch) {
    static auto real = (int (*)(int, const sigset_t*, sigset_t*))dlsym(
        RTLD_NEXT, "pthread_sigmask");
    return real(how, set, old);
  }
  return sigprocmask(how, set, old) == 0 ? 0 : errno;
}

int kill(pid_t pid, int sig) {
  if (!g_ch || (sig != 0 && !is_virt_sig(sig)))
    return (int)sys_native(SYS_kill, pid, sig);
  // Group/broadcast kills MUST stay virtual: the managed process shares
  // the driver's real process group, so a native kill(0)/kill(-1) would
  // signal the simulator itself. Wire encoding: arg2=1 marks a group kill
  // (pid 0 = caller's lineage, -1 = all managed, -g = group of leader g).
  if (pid <= 0)
    return (int)ipc_call6(SYS_kill, pid == -1 ? -1 : -pid, sig, 1);
  return (int)ipc_call6(SYS_kill, pid == getpid() ? 0 : pid, sig);
}

int raise(int sig) {
  if (!g_ch || !is_virt_sig(sig)) {
    static auto real = (int (*)(int))dlsym(RTLD_NEXT, "raise");
    return real(sig);
  }
  return (int)ipc_call6(SYS_kill, 0, sig);
}

int accept4(int fd, struct sockaddr* addr, socklen_t* alen, int flags) {
  if (!is_managed_fd(fd))
    return (int)sys_native(SYS_accept4, fd, addr, alen, flags);
  // reply data = [u32 peer_ip, u16 peer_port] packed in ret-adjacent words
  int64_t args[6] = {fd, flags, 0, 0, 0, 0};
  uint8_t out[8];
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_accept4, args, nullptr, 0, out, sizeof(out),
                       &out_len);
  if (r >= 0 && out_len >= 6 && addr && alen) {
    uint32_t ip;
    uint16_t port;
    memcpy(&ip, out, 4);
    memcpy(&port, out + 4, 2);
    fill_inet(addr, alen, ip, port);
  }
  return (int)r;
}

int accept(int fd, struct sockaddr* addr, socklen_t* alen) {
  return accept4(fd, addr, alen, 0);
}

ssize_t sendto(int fd, const void* buf, size_t n, int flags,
               const struct sockaddr* addr, socklen_t alen) {
  if (!is_managed_fd(fd))
    return sys_native(SYS_sendto, fd, buf, n, flags, addr, alen);
  uint32_t ip = 0;
  uint16_t port = 0;
  int has_addr = parse_inet(addr, alen, &ip, &port) ? 1 : 0;
  if (n > IPC_DATA_MAX) n = IPC_DATA_MAX;  // caller loops for the rest
  int64_t args[6] = {fd, (int64_t)n, flags, has_addr, ip, port};
  return (ssize_t)ipc_call(SYS_sendto, args, buf, (uint32_t)n, nullptr, 0,
                           nullptr);
}

ssize_t send(int fd, const void* buf, size_t n, int flags) {
  if (!is_managed_fd(fd)) return sys_native(SYS_sendto, fd, buf, n, flags, 0, 0);
  return sendto(fd, buf, n, flags, nullptr, 0);
}

ssize_t recvfrom(int fd, void* buf, size_t n, int flags,
                 struct sockaddr* addr, socklen_t* alen) {
  if (!is_managed_fd(fd))
    return sys_native(SYS_recvfrom, fd, buf, n, flags, addr, alen);
  size_t want = n > IPC_DATA_MAX ? IPC_DATA_MAX : n;
  int64_t args[6] = {fd, (int64_t)want, flags, addr ? 1 : 0, 0, 0};
  // reply: data = [u32 src_ip, u16 src_port, payload...]
  static thread_local uint8_t tmp[IPC_DATA_MAX];
  uint32_t out_len = 0;
  int64_t r =
      ipc_call(SYS_recvfrom, args, nullptr, 0, tmp, IPC_DATA_MAX, &out_len);
  if (r < 0) return -1;
  uint32_t hdr = 6;
  uint32_t payload = out_len > hdr ? out_len - hdr : 0;
  if (payload > want) payload = (uint32_t)want;
  if (payload && buf) memcpy(buf, tmp + hdr, payload);
  if (addr && alen && out_len >= hdr) {
    uint32_t ip;
    uint16_t port;
    memcpy(&ip, tmp, 4);
    memcpy(&port, tmp + 4, 2);
    fill_inet(addr, alen, ip, port);
  }
  return (ssize_t)r;
}

ssize_t recv(int fd, void* buf, size_t n, int flags) {
  if (!is_managed_fd(fd)) return sys_native(SYS_recvfrom, fd, buf, n, flags, 0, 0);
  return recvfrom(fd, buf, n, flags, nullptr, nullptr);
}

ssize_t read(int fd, void* buf, size_t n) {
  if (!is_managed_fd(fd)) return sys_native(SYS_read, fd, buf, n);
  // generic read (sockets, pipes, eventfds, timerfds); reply data = payload
  size_t want = n > IPC_DATA_MAX ? IPC_DATA_MAX : n;
  int64_t args[6] = {fd, (int64_t)want, 0, 0, 0, 0};
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_read, args, nullptr, 0, buf, (uint32_t)want,
                       &out_len);
  return (ssize_t)r;
}

// Sim-time line stamping for stdout/stderr (reference analog:
// shim_logger.c — managed-process log lines carry the SIMULATED clock, not
// wall time). The stamp is the channel's last-reply sim_time_ns: every
// syscall reply refreshes it, so a line printed between syscalls carries
// the time of the preceding syscall boundary — the same resolution the
// reference gets from its start-offset + emulated clock. Prefix format
// matches the driver's log lines (utils/log.py _fmt_time).
ssize_t stamped_write(int fd, const uint8_t* buf, size_t n) {
  Channel* c = cur_channel();
  if (!c) c = g_stamp_ch;
  int64_t ns = c ? c->sim_time_ns : 0;
  char pfx[40];
  int64_t us = ns / 1000;
  int64_t s = us / 1000000;
  int plen = snprintf(pfx, sizeof(pfx),
                      "%02lld:%02lld:%02lld.%06lld [stdio] ",
                      (long long)(s / 3600), (long long)(s / 60 % 60),
                      (long long)(s % 60), (long long)(us % 1000000));
  bool* bol = &g_at_bol[fd == 2 ? 1 : 0];
  // full-write helper: stdio treats a successful flush as all-or-nothing,
  // so retry short counts (pipe backpressure) until done or hard error
  auto write_all = [fd](const void* p, size_t len) -> bool {
    size_t off = 0;
    while (off < len) {
      ssize_t w = sys_native(SYS_write, fd, (const uint8_t*)p + off,
                             len - off);
      if (w < 0) {
        if (errno == EINTR) continue;
        return false;
      }
      off += (size_t)w;
    }
    return true;
  };
  size_t i = 0;
  while (i < n) {
    size_t j = i;
    while (j < n && buf[j] != '\n') j++;
    bool nl = j < n;
    if (*bol && (j > i || nl))
      if (!write_all(pfx, (size_t)plen)) return (i == 0) ? -1 : (ssize_t)i;
    size_t seg = (nl ? j + 1 : j) - i;
    if (seg && !write_all(buf + i, seg)) return (i == 0) ? -1 : (ssize_t)i;
    *bol = nl;
    i += seg ? seg : 1;
  }
  return (ssize_t)n;
}

ssize_t write(int fd, const void* buf, size_t n) {
  if (!is_managed_fd(fd)) {
    if (g_log_stamp && g_stamp_ch && (fd == 1 || fd == 2) && n)
      return stamped_write(fd, (const uint8_t*)buf, n);
    return sys_native(SYS_write, fd, buf, n);
  }
  if (n > IPC_DATA_MAX) n = IPC_DATA_MAX;  // caller loops for the rest
  int64_t args[6] = {fd, (int64_t)n, 0, 0, 0, 0};
  return (ssize_t)ipc_call(SYS_write, args, buf, (uint32_t)n, nullptr, 0,
                           nullptr);
}

ssize_t readv(int fd, const struct iovec* iov, int iovcnt) {
  if (!is_managed_fd(fd)) return sys_native(SYS_readv, fd, iov, iovcnt);
  // gather into one bounded read, then scatter across the iovecs
  static thread_local uint8_t tmp[IPC_DATA_MAX];
  size_t want = 0;
  for (int i = 0; i < iovcnt; i++) want += iov[i].iov_len;
  if (want > IPC_DATA_MAX) want = IPC_DATA_MAX;
  ssize_t r = read(fd, tmp, want);
  if (r <= 0) return r;
  size_t off = 0;
  for (int i = 0; i < iovcnt && off < (size_t)r; i++) {
    size_t take = iov[i].iov_len;
    if (take > (size_t)r - off) take = (size_t)r - off;
    memcpy(iov[i].iov_base, tmp + off, take);
    off += take;
  }
  return r;
}

ssize_t writev(int fd, const struct iovec* iov, int iovcnt) {
  if (!is_managed_fd(fd)) {
    if (g_log_stamp && g_stamp_ch && (fd == 1 || fd == 2)) {
      ssize_t total = 0;
      for (int i = 0; i < iovcnt; i++) {
        if (!iov[i].iov_len) continue;
        ssize_t w =
            stamped_write(fd, (const uint8_t*)iov[i].iov_base, iov[i].iov_len);
        if (w < 0) return total ? total : w;
        total += w;
        if ((size_t)w < iov[i].iov_len) break;
      }
      return total;
    }
    return sys_native(SYS_writev, fd, iov, iovcnt);
  }
  static thread_local uint8_t tmp[IPC_DATA_MAX];
  size_t n = 0;
  for (int i = 0; i < iovcnt; i++) {
    size_t take = iov[i].iov_len;
    if (take > IPC_DATA_MAX - n) take = IPC_DATA_MAX - n;
    memcpy(tmp + n, iov[i].iov_base, take);
    n += take;
    if (n == IPC_DATA_MAX) break;
  }
  return write(fd, tmp, n);
}

ssize_t sendmsg(int fd, const struct msghdr* msg, int flags) {
  if (!is_managed_fd(fd)) return sys_native(SYS_sendmsg, fd, msg, flags);
  static thread_local uint8_t tmp[IPC_DATA_MAX];
  size_t n = 0;
  for (size_t i = 0; i < msg->msg_iovlen; i++) {
    size_t take = msg->msg_iov[i].iov_len;
    if (take > IPC_DATA_MAX - n) take = IPC_DATA_MAX - n;
    memcpy(tmp + n, msg->msg_iov[i].iov_base, take);
    n += take;
    if (n == IPC_DATA_MAX) break;
  }
  return sendto(fd, tmp, n, flags, (const struct sockaddr*)msg->msg_name,
                (socklen_t)msg->msg_namelen);
}

ssize_t recvmsg(int fd, struct msghdr* msg, int flags) {
  if (!is_managed_fd(fd)) return sys_native(SYS_recvmsg, fd, msg, flags);
  static thread_local uint8_t tmp[IPC_DATA_MAX];
  size_t want = 0;
  for (size_t i = 0; i < msg->msg_iovlen; i++) want += msg->msg_iov[i].iov_len;
  if (want > IPC_DATA_MAX) want = IPC_DATA_MAX;
  socklen_t alen = (socklen_t)msg->msg_namelen;
  ssize_t r = recvfrom(fd, tmp, want, flags,
                       (struct sockaddr*)msg->msg_name,
                       msg->msg_name ? &alen : nullptr);
  if (r <= 0) return r;
  if (msg->msg_name) msg->msg_namelen = alen;
  size_t off = 0;
  for (size_t i = 0; i < msg->msg_iovlen && off < (size_t)r; i++) {
    size_t take = msg->msg_iov[i].iov_len;
    if (take > (size_t)r - off) take = (size_t)r - off;
    memcpy(msg->msg_iov[i].iov_base, tmp + off, take);
    off += take;
  }
  msg->msg_flags = 0;
  if (msg->msg_control) msg->msg_controllen = 0;
  return r;
}

int close(int fd) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_close, fd);
  return (int)ipc_call6(SYS_close, fd);
}

int dup(int fd) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_dup, fd);
  return (int)ipc_call6(SYS_dup, fd);
}

int dup2(int oldfd, int newfd) {
  if (!is_managed_fd(oldfd)) return (int)sys_native(SYS_dup2, oldfd, newfd);
  return (int)ipc_call6(SYS_dup2, oldfd, newfd);
}

int dup3(int oldfd, int newfd, int flags) {
  if (!is_managed_fd(oldfd)) return (int)sys_native(SYS_dup3, oldfd, newfd, flags);
  return (int)ipc_call6(SYS_dup3, oldfd, newfd, flags);
}

int pipe2(int fds[2], int flags) {
  if (!g_ch) return (int)sys_native(SYS_pipe2, fds, flags);
  // reply data = [i32 read_fd, i32 write_fd]
  int64_t args[6] = {flags, 0, 0, 0, 0, 0};
  uint8_t out[8];
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_pipe2, args, nullptr, 0, out, sizeof(out), &out_len);
  if (r < 0) return -1;
  if (out_len >= 8) {
    memcpy(&fds[0], out, 4);
    memcpy(&fds[1], out + 4, 4);
  }
  return 0;
}

int pipe(int fds[2]) { return pipe2(fds, 0); }

int eventfd(unsigned int initval, int flags) {
  if (!g_ch) return (int)sys_native(SYS_eventfd2, initval, flags);
  return (int)ipc_call6(SYS_eventfd2, initval, flags);
}

int timerfd_create(int clockid, int flags) {
  if (!g_ch) return (int)sys_native(SYS_timerfd_create, clockid, flags);
  return (int)ipc_call6(SYS_timerfd_create, clockid, flags);
}

static int64_t ts_to_ns(const struct timespec* ts) {
  return (int64_t)ts->tv_sec * 1000000000LL + ts->tv_nsec;
}

static void ns_to_ts(int64_t ns, struct timespec* ts) {
  ts->tv_sec = ns / 1000000000LL;
  ts->tv_nsec = ns % 1000000000LL;
}

int timerfd_settime(int fd, int flags, const struct itimerspec* new_value,
                    struct itimerspec* old_value) {
  if (!is_managed_fd(fd))
    return (int)sys_native(SYS_timerfd_settime, fd, flags, new_value, old_value);
  // request data = [i64 value_ns, i64 interval_ns]; reply data = old pair
  uint8_t in[16], out[16];
  int64_t v = ts_to_ns(&new_value->it_value);
  int64_t iv = ts_to_ns(&new_value->it_interval);
  memcpy(in, &v, 8);
  memcpy(in + 8, &iv, 8);
  int64_t args[6] = {fd, flags, 0, 0, 0, 0};
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_timerfd_settime, args, in, sizeof(in), out,
                       sizeof(out), &out_len);
  if (r < 0) return -1;
  if (old_value && out_len >= 16) {
    int64_t ov, oiv;
    memcpy(&ov, out, 8);
    memcpy(&oiv, out + 8, 8);
    ns_to_ts(ov, &old_value->it_value);
    ns_to_ts(oiv, &old_value->it_interval);
  }
  return 0;
}

int timerfd_gettime(int fd, struct itimerspec* curr) {
  if (!is_managed_fd(fd))
    return (int)sys_native(SYS_timerfd_gettime, fd, curr);
  uint8_t out[16];
  uint32_t out_len = 0;
  int64_t args[6] = {fd, 0, 0, 0, 0, 0};
  int64_t r = ipc_call(SYS_timerfd_gettime, args, nullptr, 0, out, sizeof(out),
                       &out_len);
  if (r < 0) return -1;
  if (curr && out_len >= 16) {
    int64_t v, iv;
    memcpy(&v, out, 8);
    memcpy(&iv, out + 8, 8);
    ns_to_ts(v, &curr->it_value);
    ns_to_ts(iv, &curr->it_interval);
  }
  return 0;
}

int signalfd(int fd, const sigset_t* mask, int flags) {
  // Virtual-signal-plane signalfd (syscall/signal.c surface): reads
  // consume the process's PENDING virtual signals matching the mask —
  // the block-then-read contract apps use with epoll event loops.
  if (!g_ch) return (int)sys_native(SYS_signalfd4, fd, mask, 8, flags);
  uint64_t m = 0;
  if (mask) memcpy(&m, mask, sizeof(m));
  if (m & ~VIRT_SIG_MASK) {
    // A non-virtualized signal (SIGWINCH, realtime, ...) never enters the
    // driver's pending queue, so an fd watching it would silently never
    // fire while the signal stays blocked natively — fail FAST instead.
    SHIM_LOG("signalfd: mask 0x%llx includes non-virtualized signals "
             "(virtual set 0x%llx) — refusing",
             (unsigned long long)m, (unsigned long long)VIRT_SIG_MASK);
    errno = EINVAL;
    return -1;
  }
  int64_t args[6] = {fd, flags, 0, 0, 0, 0};
  return (int)ipc_call(SYS_signalfd4, args, (const uint8_t*)&m, 8, nullptr,
                       0, nullptr);
}

// Shared sigmask-swap guard for the composed mask-swapping waits
// (ppoll/epoll_pwait). The kernel's atomicity guarantee holds in this
// plane because signals only deliver at syscall boundaries: a pending
// signal unblocked by the swap rides the sigprocmask REPLY (its handler
// runs before the wait is entered), which the guard reports as the
// kernel's delivery-on-entry EINTR; one arriving during the wait EINTRs
// the wait itself under the temporary mask.
static int sigmask_swap_enter(const sigset_t* sigmask, sigset_t* oldm) {
  if (!sigmask) return 0;
  // NATIVE pending signals the swap would unblock deliver inside
  // real_sigprocmask without touching g_sig_handled — probe them first
  // (sigpending reports the native plane only; virtual pending rides the
  // driver reply and bumps the counter).
  bool native_hit = false;
  sigset_t pend;
  if (sigpending(&pend) == 0) {
    for (int s = 1; s <= 64; s++)
      if (sigismember(&pend, s) && !sigismember(sigmask, s)) {
        native_hit = true;
        break;
      }
  }
  uint64_t h0 = g_sig_handled;
  sigprocmask(SIG_SETMASK, sigmask, oldm);
  if (g_sig_handled != h0 || native_hit) {
    sigprocmask(SIG_SETMASK, oldm, nullptr);
    errno = EINTR;
    return -1;
  }
  return 0;
}

static void sigmask_swap_exit(const sigset_t* sigmask,
                              const sigset_t* oldm) {
  if (!sigmask) return;
  int saved = errno;
  sigprocmask(SIG_SETMASK, oldm, nullptr);
  errno = saved;
}

int ppoll(struct pollfd* fds, nfds_t nfds, const struct timespec* ts,
          const sigset_t* sigmask) {
  if (!g_ch) {
    static auto real = (int (*)(struct pollfd*, nfds_t,
                                const struct timespec*,
                                const sigset_t*))dlsym(RTLD_NEXT, "ppoll");
    return real(fds, nfds, ts, sigmask);
  }
  if (ts && (ts->tv_sec < 0 || ts->tv_nsec < 0 ||
             ts->tv_nsec >= 1000000000L)) {
    errno = EINVAL;  // kernel contract for an invalid timespec
    return -1;
  }
  sigset_t oldm;
  if (sigmask_swap_enter(sigmask, &oldm) != 0) return -1;
  int timeout_ms = -1;
  if (ts) {
    int64_t ms = (int64_t)ts->tv_sec * 1000 + (ts->tv_nsec + 999999) / 1000000;
    timeout_ms = ms > INT_MAX ? INT_MAX : (int)ms;  // clamp, don't wrap
  }
  int r = poll(fds, nfds, timeout_ms);
  sigmask_swap_exit(sigmask, &oldm);
  return r;
}

int epoll_pwait(int epfd, struct epoll_event* evs, int maxevents,
                int timeout_ms, const sigset_t* sigmask) {
  if (!g_ch) {
    static auto real = (int (*)(int, struct epoll_event*, int, int,
                                const sigset_t*))dlsym(RTLD_NEXT,
                                                       "epoll_pwait");
    return real(epfd, evs, maxevents, timeout_ms, sigmask);
  }
  sigset_t oldm;
  if (sigmask_swap_enter(sigmask, &oldm) != 0) return -1;
  int r = epoll_wait(epfd, evs, maxevents, timeout_ms);
  sigmask_swap_exit(sigmask, &oldm);
  return r;
}

int pselect(int nfds, fd_set* rd, fd_set* wr, fd_set* ex,
            const struct timespec* ts, const sigset_t* sigmask) {
  if (!g_ch) {
    static auto real =
        (int (*)(int, fd_set*, fd_set*, fd_set*, const struct timespec*,
                 const sigset_t*))dlsym(RTLD_NEXT, "pselect");
    return real(nfds, rd, wr, ex, ts, sigmask);
  }
  if (ts && (ts->tv_sec < 0 || ts->tv_nsec < 0 ||
             ts->tv_nsec >= 1000000000L)) {
    errno = EINVAL;
    return -1;
  }
  sigset_t oldm;
  if (sigmask_swap_enter(sigmask, &oldm) != 0) return -1;
  struct timeval tv, *tvp = nullptr;
  if (ts) {
    tv.tv_sec = ts->tv_sec;
    tv.tv_usec = (ts->tv_nsec + 999) / 1000;
    if (tv.tv_usec >= 1000000) {  // round-up overflow: carry, or the
      tv.tv_sec += 1;             // kernel rejects the timeval (EINVAL)
      tv.tv_usec -= 1000000;
    }
    tvp = &tv;
  }
  int r = select(nfds, rd, wr, ex, tvp);
  sigmask_swap_exit(sigmask, &oldm);
  return r;
}

// ---------------------------------------------------------------------------
// Deterministic resource limits + usage (rlimit.c-class surface): limits
// are app-visible state, so reading the real machine's would leak
// nondeterminism across hosts; the table below is fixed per process (fork
// children inherit the current values with the copied address space).
// getrusage serves the VIRTUAL clock as CPU time.
// ---------------------------------------------------------------------------

static struct rlimit g_rlim[16];
static bool g_rlim_init = false;
static pthread_mutex_t g_rlim_mu = PTHREAD_MUTEX_INITIALIZER;

static void rlim_init_locked() {
  if (g_rlim_init) return;
  for (int i = 0; i < 16; i++) {
    g_rlim[i].rlim_cur = RLIM_INFINITY;
    g_rlim[i].rlim_max = RLIM_INFINITY;
  }
  // Soft limit must clear FD_BASE (1000) + the whole managed-fd budget:
  // the driver allocates virtual fds upward from FD_BASE, and a
  // synthesized 1024 would tell apps (and their fd-hygiene sweeps) that
  // descriptors the driver legitimately hands out cannot exist. The
  // driver clamps alloc_fd to this same value (procs/driver.VIRT_NOFILE).
  g_rlim[RLIMIT_NOFILE].rlim_cur = 65536;
  g_rlim[RLIMIT_NOFILE].rlim_max = 262144;
  g_rlim[RLIMIT_STACK].rlim_cur = 8ull << 20;
  g_rlim_init = true;
}

int getrlimit(int res, struct rlimit* rl) {
  if (!g_ch) return (int)sys_native(SYS_getrlimit, res, rl);
  if (res < 0 || res >= 16 || !rl) {
    errno = EINVAL;
    return -1;
  }
  pthread_mutex_lock(&g_rlim_mu);
  rlim_init_locked();
  *rl = g_rlim[res];
  pthread_mutex_unlock(&g_rlim_mu);
  return 0;
}

int setrlimit(int res, const struct rlimit* rl) {
  if (!g_ch) return (int)sys_native(SYS_setrlimit, res, rl);
  if (res < 0 || res >= 16 || !rl || rl->rlim_cur > rl->rlim_max) {
    errno = EINVAL;
    return -1;
  }
  pthread_mutex_lock(&g_rlim_mu);
  rlim_init_locked();
  if (rl->rlim_max > g_rlim[res].rlim_max) {
    pthread_mutex_unlock(&g_rlim_mu);
    errno = EPERM;  // raising the hard limit needs privilege — refuse
    return -1;
  }
  g_rlim[res] = *rl;
  pthread_mutex_unlock(&g_rlim_mu);
  return 0;
}

int prlimit(pid_t pid, __rlimit_resource res, const struct rlimit* nl,
            struct rlimit* ol) {
  if (!g_ch) return (int)sys_native(SYS_prlimit64, pid, res, nl, ol);
  if (pid != 0 && pid != getpid()) {
    errno = EPERM;  // cross-process limits stay out of the sim plane
    return -1;
  }
  if (ol && getrlimit(res, ol) != 0) return -1;
  if (nl) return setrlimit(res, nl);
  return 0;
}

int prlimit64(pid_t pid, __rlimit_resource res, const struct rlimit64* nl,
              struct rlimit64* ol) {
  // x86_64: rlimit == rlimit64 (both 64-bit fields)
  return prlimit(pid, res, (const struct rlimit*)nl, (struct rlimit*)ol);
}

int getrusage(int who, struct rusage* ru) {
  if (!g_ch) return (int)sys_native(SYS_getrusage, who, ru);
  if (!ru) {
    errno = EFAULT;
    return -1;
  }
  // Deterministic synthesis: CPU time = the virtual clock (the CPU model
  // charges simulated processing to it), everything else fixed. Only
  // RUSAGE_SELF carries the clock: children's accumulated time (and
  // per-thread time) report zero — the Linux baseline for a process that
  // has reaped nothing.
  memset(ru, 0, sizeof(*ru));
  if (who == RUSAGE_SELF) {
    Channel* c = cur_channel();
    uint64_t ns = c ? (uint64_t)c->sim_time_ns : 0;
    ru->ru_utime.tv_sec = (time_t)(ns / 1000000000ull);
    ru->ru_utime.tv_usec = (suseconds_t)((ns % 1000000000ull) / 1000);
  }
  ru->ru_maxrss = 65536;  // fixed 64 MiB in KB — deterministic
  return 0;
}

// Virtualized CPU visibility: the driver reports the simulated host's
// CPU count (default 1 — matching the one-runnable-thread determinism
// model), so glibc's __get_nprocs / sysconf(_SC_NPROCESSORS_ONLN) and
// app thread-pool sizing are deterministic instead of leaking the real
// machine's core count. (The reference pins workers but lets nproc
// leak; Tor sizes its threadpool from it — determinism wants this.)
// Returns the RAW KERNEL convention (size of the kernel cpumask copy,
// or -errno) — the SIGSYS dispatcher forwards it as-is; the libc-facing
// wrapper below converts to glibc's 0-on-success.
long sched_getaffinity_raw(pid_t pid, size_t cpusetsize, cpu_set_t* mask) {
  int64_t args[6] = {pid, (int64_t)cpusetsize, 0, 0, 0, 0};
  uint32_t out_len = 0;
  uint8_t tmp[128];
  int64_t r = ipc_call(SYS_sched_getaffinity, args, nullptr, 0, tmp,
                       sizeof(tmp), &out_len);
  if (r < 0) return -(long)errno;
  if (mask && cpusetsize) {
    memset(mask, 0, cpusetsize);
    size_t n = out_len < cpusetsize ? out_len : cpusetsize;
    memcpy(mask, tmp, n);
  }
  return (long)r;
}

int sched_getaffinity(pid_t pid, size_t cpusetsize, cpu_set_t* mask) {
  if (!g_ch)
    return (int)sys_native(SYS_sched_getaffinity, pid, cpusetsize, mask) < 0
               ? -1
               : 0;
  long r = sched_getaffinity_raw(pid, cpusetsize, mask);
  if (r < 0) {
    errno = (int)-r;
    return -1;
  }
  return 0;  // glibc convention
}

long sysconf(int name) {
  static auto real_sysconf = (long (*)(int))dlsym(RTLD_NEXT, "sysconf");
  // glibc's __get_nprocs reads /sys (the REAL machine) on modern
  // versions, so the processor-count queries are answered from the
  // virtualized affinity mask instead.
  if (g_ch && (name == _SC_NPROCESSORS_ONLN || name == _SC_NPROCESSORS_CONF)) {
    cpu_set_t s;
    CPU_ZERO(&s);
    if (sched_getaffinity(0, sizeof(s), &s) == 0) {
      int n = CPU_COUNT(&s);
      if (n > 0) return n;
    }
    return 1;
  }
  return real_sysconf(name);
}

ssize_t getrandom(void* buf, size_t buflen, unsigned int flags) {
  if (!g_ch) return sys_native(SYS_getrandom, buf, buflen, flags);
  // deterministic per-host stream from the simulator's seeded RNG tree
  size_t want = buflen > IPC_DATA_MAX ? IPC_DATA_MAX : buflen;
  int64_t args[6] = {(int64_t)want, flags, 0, 0, 0, 0};
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_getrandom, args, nullptr, 0, buf, (uint32_t)want,
                       &out_len);
  return (ssize_t)r;
}

int shutdown(int fd, int how) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_shutdown, fd, how);
  return (int)ipc_call6(SYS_shutdown, fd, how);
}

int setsockopt(int fd, int level, int optname, const void* optval,
               socklen_t optlen) {
  if (!is_managed_fd(fd))
    return (int)sys_native(SYS_setsockopt, fd, level, optname, optval, optlen);
  int64_t v = 0;
  if (optval && optlen >= sizeof(int)) v = *(const int*)optval;
  return (int)ipc_call6(SYS_setsockopt, fd, level, optname, v);
}

int getsockopt(int fd, int level, int optname, void* optval,
               socklen_t* optlen) {
  if (!is_managed_fd(fd))
    return (int)sys_native(SYS_getsockopt, fd, level, optname, optval, optlen);
  int64_t r = ipc_call6(SYS_getsockopt, fd, level, optname);
  if (r < 0) return -1;
  if (optval && optlen && *optlen >= sizeof(int)) {
    *(int*)optval = (int)r;
    *optlen = sizeof(int);
  }
  return 0;
}

int getsockname(int fd, struct sockaddr* addr, socklen_t* alen) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_getsockname, fd, addr, alen);
  uint8_t out[8];
  uint32_t out_len = 0;
  int64_t args[6] = {fd, 0, 0, 0, 0, 0};
  int64_t r =
      ipc_call(SYS_getsockname, args, nullptr, 0, out, sizeof(out), &out_len);
  if (r < 0) return -1;
  if (out_len >= 6) {
    uint32_t ip;
    uint16_t port;
    memcpy(&ip, out, 4);
    memcpy(&port, out + 4, 2);
    fill_inet(addr, alen, ip, port);
  }
  return 0;
}

int getpeername(int fd, struct sockaddr* addr, socklen_t* alen) {
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_getpeername, fd, addr, alen);
  uint8_t out[8];
  uint32_t out_len = 0;
  int64_t args[6] = {fd, 0, 0, 0, 0, 0};
  int64_t r =
      ipc_call(SYS_getpeername, args, nullptr, 0, out, sizeof(out), &out_len);
  if (r < 0) return -1;
  if (out_len >= 6) {
    uint32_t ip;
    uint16_t port;
    memcpy(&ip, out, 4);
    memcpy(&port, out + 4, 2);
    fill_inet(addr, alen, ip, port);
  }
  return 0;
}

int fcntl(int fd, int cmd, ...) {
  va_list ap;
  va_start(ap, cmd);
  long arg = va_arg(ap, long);
  va_end(ap);
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_fcntl, fd, cmd, arg);
  return (int)ipc_call6(SYS_fcntl, fd, cmd, arg);
}

int ioctl(int fd, unsigned long req, ...) {
  va_list ap;
  va_start(ap, req);
  void* argp = va_arg(ap, void*);
  va_end(ap);
  if (!is_managed_fd(fd)) return (int)sys_native(SYS_ioctl, fd, req, argp);
  // FIONREAD is the one sockets commonly use
  int64_t r = ipc_call6(SYS_ioctl, fd, (int64_t)req);
  if (r < 0) return -1;
  if (argp) *(int*)argp = (int)r;
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// time virtualization (reference analog: shim_syscall.c time cache +
// clock_gettime interposition; sim time is authoritative)
// ---------------------------------------------------------------------------

extern "C" {

int clock_gettime(clockid_t clk, struct timespec* tp) {
  if (!g_ch) return (int)sys_native(SYS_clock_gettime, clk, tp);
  int64_t r = ipc_call6(SYS_clock_gettime, clk);
  if (r < 0) return -1;
  if (tp) {
    tp->tv_sec = r / 1000000000LL;
    tp->tv_nsec = r % 1000000000LL;
  }
  return 0;
}

int gettimeofday(struct timeval* tv, void* tz) {
  (void)tz;
  if (!g_ch) return (int)sys_native(SYS_gettimeofday, tv, tz);
  struct timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) return -1;
  if (tv) {
    tv->tv_sec = ts.tv_sec;
    tv->tv_usec = ts.tv_nsec / 1000;
  }
  return 0;
}

time_t time(time_t* t) {
  if (!g_ch) {
    struct timespec ts;
    sys_native(SYS_clock_gettime, CLOCK_REALTIME, &ts);
    if (t) *t = ts.tv_sec;
    return ts.tv_sec;
  }
  struct timespec ts;
  if (clock_gettime(CLOCK_REALTIME, &ts) != 0) return (time_t)-1;
  if (t) *t = ts.tv_sec;
  return ts.tv_sec;
}

int nanosleep(const struct timespec* req, struct timespec* rem) {
  if (!g_ch) return (int)sys_native(SYS_nanosleep, req, rem);
  if (!req) {
    errno = EFAULT;
    return -1;
  }
  int64_t ns = (int64_t)req->tv_sec * 1000000000LL + req->tv_nsec;
  int64_t r = ipc_call6(SYS_nanosleep, ns);
  if (rem) {
    rem->tv_sec = 0;
    rem->tv_nsec = 0;
  }
  return r < 0 ? -1 : 0;
}

unsigned int sleep(unsigned int seconds) {
  struct timespec ts = {(time_t)seconds, 0};
  nanosleep(&ts, nullptr);
  return 0;
}

int usleep(useconds_t usec) {
  struct timespec ts = {(time_t)(usec / 1000000),
                        (long)(usec % 1000000) * 1000};
  return nanosleep(&ts, nullptr);
}

// ---------------------------------------------------------------------------
// readiness: epoll / poll / select
// ---------------------------------------------------------------------------

int epoll_create1(int flags) {
  if (!g_ch) return (int)sys_native(SYS_epoll_create1, flags);
  return (int)ipc_call6(SYS_epoll_create1, flags);
}

int epoll_create(int size) {
  (void)size;
  return epoll_create1(0);
}

int epoll_ctl(int epfd, int op, int fd, struct epoll_event* ev) {
  if (!is_managed_fd(epfd))
    return (int)sys_native(SYS_epoll_ctl, epfd, op, fd, ev);
  int64_t events = ev ? (int64_t)ev->events : 0;
  int64_t data = ev ? (int64_t)ev->data.u64 : 0;
  return (int)ipc_call6(SYS_epoll_ctl, epfd, op, fd, events, data);
}

int epoll_wait(int epfd, struct epoll_event* evs, int maxevents,
               int timeout_ms) {
  if (!is_managed_fd(epfd))
    return (int)sys_native(SYS_epoll_wait, epfd, evs, maxevents, timeout_ms);
  // reply data = maxevents × {u32 events, u64 data} packed (12 bytes each)
  int want = maxevents;
  if (want > (int)(IPC_DATA_MAX / 12)) want = IPC_DATA_MAX / 12;
  int64_t args[6] = {epfd, want, timeout_ms, 0, 0, 0};
  static thread_local uint8_t tmp[IPC_DATA_MAX];
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_epoll_wait, args, nullptr, 0, tmp, IPC_DATA_MAX,
                       &out_len);
  if (r < 0) return -1;
  int nready = (int)r;
  for (int i = 0; i < nready && (uint32_t)(i * 12 + 12) <= out_len; i++) {
    uint32_t e;
    uint64_t d;
    memcpy(&e, tmp + i * 12, 4);
    memcpy(&d, tmp + i * 12 + 4, 8);
    evs[i].events = e;
    evs[i].data.u64 = d;
  }
  return nready;
}

int poll(struct pollfd* fds, nfds_t nfds, int timeout_ms) {
  bool any_managed = false;
  for (nfds_t i = 0; i < nfds; i++)
    if (is_managed_fd(fds[i].fd)) any_managed = true;
  if (!any_managed) return (int)sys_native(SYS_poll, fds, nfds, timeout_ms);
  // request data = nfds × {i32 fd, i16 events} (6 bytes); native fds in a
  // mixed set are reported to the driver too (it treats them as never
  // ready — a documented v1 simplification).
  if (nfds > IPC_DATA_MAX / 6) nfds = IPC_DATA_MAX / 6;
  static thread_local uint8_t tmp[IPC_DATA_MAX];
  for (nfds_t i = 0; i < nfds; i++) {
    int32_t fd = fds[i].fd;
    int16_t ev = fds[i].events;
    memcpy(tmp + i * 6, &fd, 4);
    memcpy(tmp + i * 6 + 4, &ev, 2);
  }
  int64_t args[6] = {(int64_t)nfds, timeout_ms, 0, 0, 0, 0};
  static thread_local uint8_t out[IPC_DATA_MAX];
  uint32_t out_len = 0;
  int64_t r = ipc_call(SYS_poll, args, tmp, (uint32_t)(nfds * 6), out,
                       IPC_DATA_MAX, &out_len);
  if (r < 0) return -1;
  // reply data = nfds × i16 revents
  for (nfds_t i = 0; i < nfds && (uint32_t)(i * 2 + 2) <= out_len; i++) {
    int16_t rev;
    memcpy(&rev, out + i * 2, 2);
    fds[i].revents = rev;
  }
  return (int)r;
}

int select(int nfds, fd_set* rd, fd_set* wr, fd_set* ex,
           struct timeval* timeout) {
  bool any_managed = false;
  for (int fd = FD_BASE; fd < nfds; fd++) {
    if ((rd && FD_ISSET(fd, rd)) || (wr && FD_ISSET(fd, wr)) ||
        (ex && FD_ISSET(fd, ex)))
      any_managed = true;
  }
  if (!g_ch || !any_managed)
    return (int)sys_native(SYS_select, nfds, rd, wr, ex, timeout);
  // convert to a pollfd set over the managed fds, forward as poll
  struct pollfd pfds[64];
  int n = 0;
  for (int fd = 0; fd < nfds && n < 64; fd++) {
    short ev = 0;
    if (rd && FD_ISSET(fd, rd)) ev |= POLLIN;
    if (wr && FD_ISSET(fd, wr)) ev |= POLLOUT;
    if (ex && FD_ISSET(fd, ex)) ev |= POLLERR;
    if (ev) {
      pfds[n].fd = fd;
      pfds[n].events = ev;
      pfds[n].revents = 0;
      n++;
    }
  }
  int timeout_ms = -1;
  if (timeout)
    timeout_ms = (int)(timeout->tv_sec * 1000 + timeout->tv_usec / 1000);
  int r = poll(pfds, n, timeout_ms);
  if (r < 0) return -1;
  if (rd) FD_ZERO(rd);
  if (wr) FD_ZERO(wr);
  if (ex) FD_ZERO(ex);
  int count = 0;
  for (int i = 0; i < n; i++) {
    if (pfds[i].revents & (POLLIN | POLLHUP)) {
      if (rd) {
        FD_SET(pfds[i].fd, rd);
        count++;
      }
    }
    if (pfds[i].revents & POLLOUT) {
      if (wr) {
        FD_SET(pfds[i].fd, wr);
        count++;
      }
    }
    if (pfds[i].revents & POLLERR) {
      if (ex) {
        FD_SET(pfds[i].fd, ex);
        count++;
      }
    }
  }
  return count;
}

// ---------------------------------------------------------------------------
// name resolution (reference analog: preload_libraries.c getaddrinfo via a
// custom simulator-side resolution syscall + DNS registry in routing/dns.c)
// ---------------------------------------------------------------------------

int getaddrinfo(const char* node, const char* service,
                const struct addrinfo* hints, struct addrinfo** res) {
  if (!g_ch) return EAI_FAIL;  // no native fallback under the simulator
  uint32_t ip = 0;
  if (node) {
    struct in_addr a;
    if (inet_aton(node, &a)) {
      ip = ntohl(a.s_addr);
    } else {
      int64_t args[6] = {0, 0, 0, 0, 0, 0};
      int64_t r = ipc_call(PSYS_RESOLVE_NAME, args, node,
                           (uint32_t)strlen(node), nullptr, 0, nullptr);
      if (r < 0) return EAI_NONAME;
      ip = (uint32_t)r;
    }
  } else {
    ip = INADDR_LOOPBACK;
  }
  uint16_t port = 0;
  if (service) port = (uint16_t)atoi(service);

  struct addrinfo* ai = (struct addrinfo*)calloc(1, sizeof(struct addrinfo));
  struct sockaddr_in* sin =
      (struct sockaddr_in*)calloc(1, sizeof(struct sockaddr_in));
  sin->sin_family = AF_INET;
  sin->sin_addr.s_addr = htonl(ip);
  sin->sin_port = htons(port);
  ai->ai_family = AF_INET;
  ai->ai_socktype = hints ? hints->ai_socktype : SOCK_STREAM;
  ai->ai_protocol = hints ? hints->ai_protocol : 0;
  ai->ai_addrlen = sizeof(struct sockaddr_in);
  ai->ai_addr = (struct sockaddr*)sin;
  *res = ai;
  return 0;
}

void freeaddrinfo(struct addrinfo* res) {
  while (res) {
    struct addrinfo* next = res->ai_next;
    free(res->ai_addr);
    free(res);
    res = next;
  }
}

int gethostname(char* name, size_t len) {
  if (len == 0) {
    // len-1 below would underflow to SIZE_MAX and overrun a 0-byte buffer
    errno = EINVAL;
    return -1;
  }
  if (!g_ch) {
    struct utsname u;
    if (sys_native(SYS_uname, &u) != 0) return -1;
    size_t want = strlen(u.nodename);
    size_t m = want < len - 1 ? want : len - 1;
    memcpy(name, u.nodename, m);
    name[m] = 0;
    return 0;
  }
  static thread_local char tmp[256];
  uint32_t out_len = 0;
  int64_t args[6] = {0, 0, 0, 0, 0, 0};
  int64_t r = ipc_call(PSYS_GETHOSTNAME, args, nullptr, 0, tmp, sizeof(tmp),
                       &out_len);
  if (r < 0) return -1;
  size_t n = out_len < len - 1 ? out_len : len - 1;
  memcpy(name, tmp, n);
  name[n] = 0;
  return 0;
}

// ---------------------------------------------------------------------------
// Minimal /proc virtualization: the CPU-count pseudo-files. Apps (and
// glibc's __get_nprocs on /sys-reading versions) that COUNT CPUS from
// files must see the simulated host's count, not the real machine's.
// A matching open returns an anonymous memfd holding synthesized content;
// everything else opens natively. (Reference analog: Shadow does not
// virtualize /proc either, but its processes are pinned; our determinism
// story makes nproc part of the simulation contract — see
// sched_getaffinity above.)
// ---------------------------------------------------------------------------

long virt_cpu_file_open(const char* path) {
  // returns a ready-to-read fd, or -1 when the path is not virtualized
  if (!g_ch || !path) return -1;
  if (strcmp(path, "/proc/cpuinfo") != 0 &&
      strcmp(path, "/sys/devices/system/cpu/online") != 0 &&
      strcmp(path, "/sys/devices/system/cpu/possible") != 0)
    return -1;
  cpu_set_t s;
  CPU_ZERO(&s);
  int ncpu = 1;
  if (sched_getaffinity_raw(0, sizeof(s), &s) > 0) {
    int n = CPU_COUNT(&s);
    if (n > 0) ncpu = n;
  }
  char buf[4096];
  size_t off = 0;
  if (strcmp(path, "/proc/cpuinfo") == 0) {
    for (int i = 0; i < ncpu && off + 64 < sizeof(buf); i++)
      off += (size_t)snprintf(buf + off, sizeof(buf) - off,
                              "processor\t: %d\nmodel name\t: simulated\n\n",
                              i);
  } else {
    off = (size_t)(ncpu > 1
                       ? snprintf(buf, sizeof(buf), "0-%d\n", ncpu - 1)
                       : snprintf(buf, sizeof(buf), "0\n"));
  }
  long fd = shim_gate_syscall(SYS_memfd_create, (long)"cpu_virt", 0, 0, 0, 0,
                              0);
  if (fd < 0) return -1;
  size_t w = 0;
  while (w < off) {
    long r = shim_gate_syscall(SYS_write, fd, (long)(buf + w), off - w, 0, 0,
                               0);
    if (r <= 0) {
      shim_gate_syscall(SYS_close, fd, 0, 0, 0, 0, 0);
      return -1;
    }
    w += (size_t)r;
  }
  shim_gate_syscall(SYS_lseek, fd, 0, SEEK_SET, 0, 0, 0);
  return fd;
}

int uname(struct utsname* buf) {
  long r = sys_native(SYS_uname, buf);
  if (r < 0 || !g_ch || !buf) return r < 0 ? -1 : 0;
  // nodename must agree with the simulated hostname (gethostname above) —
  // apps commonly identify themselves via uname and the real machine's
  // name leaking in would break determinism comparisons across machines
  char hn[sizeof(buf->nodename)];
  if (gethostname(hn, sizeof(hn)) == 0) {
    memset(buf->nodename, 0, sizeof(buf->nodename));
    strncpy(buf->nodename, hn, sizeof(buf->nodename) - 1);
  }
  return 0;
}

int clock_nanosleep(clockid_t clk, int flags, const struct timespec* req,
                    struct timespec* rem) {
  if (!g_ch) {
    // clock_nanosleep returns the error value directly (no errno)
    long r = shim_gate_syscall(SYS_clock_nanosleep, clk, flags, (long)req,
                               (long)rem, 0, 0);
    return r < 0 ? (int)-r : 0;
  }
  if (!req) return EFAULT;  // clock_nanosleep returns the error directly
  if (flags & TIMER_ABSTIME) {
    struct timespec now;
    clock_gettime(clk, &now);
    int64_t d = ts_to_ns(req) - ts_to_ns(&now);
    if (d <= 0) return 0;
    struct timespec rel;
    ns_to_ts(d, &rel);
    return nanosleep(&rel, rem) < 0 ? errno : 0;
  }
  return nanosleep(req, rem) < 0 ? errno : 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// wider libc surface (VERDICT r4 #5): stat on managed fds, interface
// enumeration, deterministic localtime, and the memory-map policy.
// Reference analogs: syscall_handler.c stat dispatch rows,
// preload_libraries.c:31-652 (getifaddrs/localtime), and
// memory_manager/memory_mapper.rs:66-95 (mmap interception — here a
// policy refusal: plugin memory is process-local by design, so only
// sharing-capable mappings need denying).
// ---------------------------------------------------------------------------

extern "C" {

// raw-kernel convention (-errno) → libc convention (-1 + errno)
#define RAWRET_INV(call)                    \
  ({                                        \
    long _r = (long)(call);                 \
    if (_r < 0) {                           \
      errno = (int)-_r;                     \
      _r = -1;                              \
    }                                       \
    _r;                                     \
  })

int fstat(int fd, struct stat* st) {
  if (!is_managed_fd(fd))
    return (int)RAWRET_INV(sys_native(SYS_fstat, fd, st));
  int64_t kind = ipc_call6(PSYS_FSTAT, fd);
  if (kind < 0) return -1;  // errno set by ipc_call
  memset(st, 0, sizeof(*st));
  switch ((int)kind) {
    case FD_KIND_SOCKET:
      st->st_mode = S_IFSOCK | 0777;
      break;
    case FD_KIND_PIPE:
      st->st_mode = S_IFIFO | 0600;
      break;
    default:  // eventfd/timerfd/epoll present as anonymous inodes
      st->st_mode = S_IFCHR | 0600;
      break;
  }
  st->st_nlink = 1;
  st->st_blksize = 4096;
  return 0;
}

int fstat64(int fd, struct stat64* st) {
  return fstat(fd, (struct stat*)st);  // identical layout on x86_64
}

int statx(int dirfd, const char* path, int flags, unsigned int mask,
          struct statx* stx) {
  // modern glibc/Rust stat entry point; managed dirfd with an empty path
  // (AT_EMPTY_PATH) is an fstat in disguise. The NULL test must go
  // through a volatile copy: glibc declares the parameter nonnull, so
  // -O2 would otherwise DELETE the check — and the raw-trap route feeds
  // NULL here legitimately (statx(fd, NULL, AT_EMPTY_PATH, ...) is valid
  // since Linux 6.11).
  const char* volatile vpath = path;
  if (is_managed_fd(dirfd) && (flags & AT_EMPTY_PATH) &&
      (vpath == nullptr || vpath[0] == 0)) {
    struct stat st;
    if (fstat(dirfd, &st) != 0) return -1;
    memset(stx, 0, sizeof(*stx));
    stx->stx_mask = STATX_TYPE | STATX_MODE | STATX_NLINK;
    stx->stx_mode = (uint16_t)st.st_mode;
    stx->stx_nlink = (uint32_t)st.st_nlink;
    stx->stx_blksize = (uint32_t)st.st_blksize;
    return 0;
  }
  return (int)RAWRET_INV(sys_native(SYS_statx, dirfd, path, flags, mask,
                                    stx));
}

int fstatat(int dirfd, const char* path, struct stat* st, int flags) {
  if (is_managed_fd(dirfd) && (!path || !path[0]))
    return fstat(dirfd, st);  // AT_EMPTY_PATH form glibc uses for fstat
  // sys_native (the IP-whitelisted gate), NEVER plain syscall(): the raw
  // instruction would re-trap the seccomp filter forever — and the FD0
  // discriminator compares arg0 low-32 UNSIGNED, so AT_FDCWD (-100)
  // traps every path-based stat through here
  return (int)RAWRET_INV(sys_native(SYS_newfstatat, dirfd, path, st,
                                    flags));
}

// Interface enumeration (preload_libraries.c getifaddrs analog): lo plus
// one eth0 carrying this host's simulated address. Allocated as a single
// block; freeifaddrs releases it whole.
struct ShimIfBlock {
  struct ifaddrs ifa[2];
  struct sockaddr_in addr[2];
  struct sockaddr_in mask[2];
  struct sockaddr_in bcast[2];
  char names[2][8];
};

int getifaddrs(struct ifaddrs** out) {
  if (!g_ch) {
    errno = ENOSYS;  // no native fallback under the simulator
    return -1;
  }
  char host[256];
  if (gethostname(host, sizeof host) != 0) return -1;
  int64_t args[6] = {0, 0, 0, 0, 0, 0};
  int64_t ip = ipc_call(PSYS_RESOLVE_NAME, args, host,
                        (uint32_t)strlen(host), nullptr, 0, nullptr);
  if (ip < 0) return -1;
  ShimIfBlock* b = (ShimIfBlock*)calloc(1, sizeof(ShimIfBlock));
  if (!b) {
    errno = ENOMEM;
    return -1;
  }
  strcpy(b->names[0], "lo");
  strcpy(b->names[1], "eth0");
  uint32_t ips[2] = {INADDR_LOOPBACK, (uint32_t)ip};
  uint32_t masks[2] = {0xFF000000u, 0xFFFFFF00u};
  unsigned int fl[2] = {IFF_UP | IFF_RUNNING | IFF_LOOPBACK,
                        IFF_UP | IFF_RUNNING | IFF_BROADCAST};
  for (int i = 0; i < 2; i++) {
    b->addr[i].sin_family = AF_INET;
    b->addr[i].sin_addr.s_addr = htonl(ips[i]);
    b->mask[i].sin_family = AF_INET;
    b->mask[i].sin_addr.s_addr = htonl(masks[i]);
    b->bcast[i].sin_family = AF_INET;
    b->bcast[i].sin_addr.s_addr = htonl(ips[i] | ~masks[i]);
    b->ifa[i].ifa_name = b->names[i];
    b->ifa[i].ifa_flags = fl[i];
    b->ifa[i].ifa_addr = (struct sockaddr*)&b->addr[i];
    b->ifa[i].ifa_netmask = (struct sockaddr*)&b->mask[i];
    if (fl[i] & IFF_BROADCAST)  // contract: broadaddr valid when flagged
      b->ifa[i].ifa_broadaddr = (struct sockaddr*)&b->bcast[i];
    b->ifa[i].ifa_next = i == 0 ? &b->ifa[1] : nullptr;
  }
  *out = &b->ifa[0];
  return 0;
}

void freeifaddrs(struct ifaddrs* ifa) {
  free(ifa);  // head of the single ShimIfBlock allocation
}

// Deterministic local time (preload_libraries.c localtime analog): the
// simulated clock is already served by time()/clock_gettime(); pinning the
// zone to UTC removes the host machine's /etc/localtime from results, so
// runs reproduce across machines.
struct tm* localtime_r(const time_t* t, struct tm* out) {
  return gmtime_r(t, out);
}

struct tm* localtime(const time_t* t) {
  static thread_local struct tm buf;
  return gmtime_r(t, &buf);
}

// Memory-map policy (memory_mapper.rs:66-95 analog, inverted: the
// reference remaps plugin memory into the simulator; here plugin memory
// is process-local by design, so mmap runs native EXCEPT where a mapping
// could smuggle nondeterministic shared state past the simulated I/O
// plane: writable file-backed MAP_SHARED is refused, and managed fds are
// not mappable at all. The shim's own channel mappings use raw syscalls
// and bypass this.
void* mmap(void* addr, size_t len, int prot, int flags, int fd, off_t off) {
  if (g_ch) {
    if (is_managed_fd(fd)) {
      errno = ENODEV;
      return MAP_FAILED;
    }
    if (fd >= 0 && (flags & MAP_SHARED) && (prot & PROT_WRITE)) {
      SHIM_LOG("mmap policy: refusing writable MAP_SHARED of fd %d", fd);
      errno = EACCES;
      return MAP_FAILED;
    }
    if (fd < 0 && (flags & MAP_SHARED) && (flags & MAP_ANONYMOUS) &&
        (prot & PROT_WRITE)) {
      // Consistent policy (ADVICE r4): a fork-inherited anonymous shared
      // mapping is exactly the cross-process shared-state channel the
      // file-backed refusal exists to deny — an app coordinating through
      // it would bypass the simulated I/O plane just the same.
      SHIM_LOG("mmap policy: refusing writable anonymous MAP_SHARED");
      errno = EACCES;
      return MAP_FAILED;
    }
  }
  return (void*)sys_native(SYS_mmap, (long)addr, (long)len, (long)prot,
                           (long)flags, (long)fd, (long)off);
}

void* mmap64(void* addr, size_t len, int prot, int flags, int fd,
             off64_t off) {
  return mmap(addr, len, prot, flags, fd, (off_t)off);
}

// ---------------------------------------------------------------------------
// /proc/self/fd DIRECTORY LISTING with managed fds merged in: the kernel's
// listing only shows real fds, so an app enumerating its descriptors (fd
// hygiene sweeps, close-range fallbacks) would miss every simulated
// socket/pipe/timer. opendir on the fd directory returns a synthetic
// stream of real entries (from the kernel) plus the driver's open managed
// fds (PSYS_FD_LIST). glibc-INTERNAL opendir calls (e.g. scandir) bypass
// PLT interposition and still see only real fds — documented limitation.
// ---------------------------------------------------------------------------

struct VirtFdDir {
  long fds[1024];
  int count;
  int pos;
  int backing_fd;  // real O_DIRECTORY fd: dirfd() identity for skip logic
  struct dirent ent;
};

// Registry slots are atomics: readdir/closedir on ORDINARY directory
// streams must not take a process-wide lock — the hot-path membership
// check is a handful of relaxed loads; the mutex only serializes open
// registration.
static std::atomic<VirtFdDir*> g_vdirs[64];
static pthread_mutex_t g_vdir_mu = PTHREAD_MUTEX_INITIALIZER;

static bool is_proc_fd_dir(const char* name) {
  if (!name) return false;
  if (strcmp(name, "/proc/self/fd") == 0 ||
      strcmp(name, "/proc/self/fd/") == 0 ||
      strcmp(name, "/dev/fd") == 0 ||  // the portable alias (symlink to
      strcmp(name, "/dev/fd/") == 0)   // /proc/self/fd; BSD-derived code)
    return true;
  char buf[64];
  snprintf(buf, sizeof buf, "/proc/%d/fd", (int)getpid());
  size_t n = strlen(buf);
  return strncmp(name, buf, n) == 0 &&
         (name[n] == 0 || (name[n] == '/' && name[n + 1] == 0));
}

static VirtFdDir* vdir_of(DIR* dp) {
  for (auto& slot : g_vdirs)
    if (slot.load(std::memory_order_relaxed) == (VirtFdDir*)dp)
      return (VirtFdDir*)dp;
  return nullptr;
}

DIR* opendir(const char* name) {
  static auto real_opendir = (DIR * (*)(const char*)) dlsym(RTLD_NEXT,
                                                            "opendir");
  static auto real_readdir =
      (struct dirent * (*)(DIR*)) dlsym(RTLD_NEXT, "readdir");
  static auto real_closedir = (int (*)(DIR*))dlsym(RTLD_NEXT, "closedir");
  if (!g_ch || !is_proc_fd_dir(name)) return real_opendir(name);
  VirtFdDir* d = (VirtFdDir*)calloc(1, sizeof(VirtFdDir));
  if (!d) return nullptr;
  // real directory fd FIRST: dirfd() must return a live fd that appears
  // in the listing, exactly like a kernel DIR (fd-hygiene sweeps skip it)
  d->backing_fd = (int)sys_native(SYS_open, (long)name,
                                  O_RDONLY | O_DIRECTORY, 0);
  DIR* rd = real_opendir(name);
  if (rd) {
    struct dirent* e;
    while ((e = real_readdir(rd)) && d->count < 1000) {
      if (e->d_name[0] == '.') continue;
      char* end = nullptr;
      long fd = strtol(e->d_name, &end, 10);
      if (end && *end == 0) d->fds[d->count++] = fd;
    }
    real_closedir(rd);
  }
  int64_t args[6] = {0, 0, 0, 0, 0, 0};
  static thread_local uint8_t out[IPC_DATA_MAX];
  uint32_t out_len = 0;
  int64_t r = ipc_call(PSYS_FD_LIST, args, nullptr, 0, out, IPC_DATA_MAX,
                       &out_len);
  for (int i = 0; r > 0 && i < (int)r && d->count < 1024 &&
                  (uint32_t)(i * 4 + 4) <= out_len;
       i++) {
    int32_t fd;
    memcpy(&fd, out + i * 4, 4);
    d->fds[d->count++] = fd;
  }
  bool registered = false;
  pthread_mutex_lock(&g_vdir_mu);
  for (auto& slot : g_vdirs)
    if (slot.load(std::memory_order_relaxed) == nullptr) {
      slot.store(d, std::memory_order_release);
      registered = true;
      break;
    }
  pthread_mutex_unlock(&g_vdir_mu);
  if (!registered) {
    // registry exhausted: the kernel-only view would NONDETERMINISTICALLY
    // hide managed fds depending on open-stream count — be loud about it
    SHIM_LOG("opendir(%s): virtual-dir registry full (64 streams); "
             "falling back to the kernel view WITHOUT managed fds", name);
    if (d->backing_fd >= 0) sys_native(SYS_close, d->backing_fd);
    free(d);
    return real_opendir(name);
  }
  return (DIR*)d;
}

int dirfd(DIR* dp) {
  static auto real_dirfd = (int (*)(DIR*))dlsym(RTLD_NEXT, "dirfd");
  VirtFdDir* d = vdir_of(dp);
  if (!d) return real_dirfd(dp);
  if (d->backing_fd < 0) {
    errno = EINVAL;
    return -1;
  }
  return d->backing_fd;
}

void rewinddir(DIR* dp) {
  static auto real_rewinddir = (void (*)(DIR*))dlsym(RTLD_NEXT, "rewinddir");
  VirtFdDir* d = vdir_of(dp);
  if (!d) {
    real_rewinddir(dp);
    return;
  }
  d->pos = 0;  // replay the open-time snapshot (proc listings are
               // snapshots under the kernel too)
}

long telldir(DIR* dp) {
  static auto real_telldir = (long (*)(DIR*))dlsym(RTLD_NEXT, "telldir");
  VirtFdDir* d = vdir_of(dp);
  if (!d) return real_telldir(dp);
  return d->pos;
}

void seekdir(DIR* dp, long loc) {
  static auto real_seekdir = (void (*)(DIR*, long))dlsym(RTLD_NEXT,
                                                         "seekdir");
  VirtFdDir* d = vdir_of(dp);
  if (!d) {
    real_seekdir(dp, loc);
    return;
  }
  if (loc >= 0 && loc <= d->count) d->pos = (int)loc;
}

struct dirent* readdir(DIR* dp) {
  static auto real_readdir =
      (struct dirent * (*)(DIR*)) dlsym(RTLD_NEXT, "readdir");
  VirtFdDir* d = vdir_of(dp);
  if (!d) return real_readdir(dp);
  if (d->pos >= d->count) return nullptr;
  long fd = d->fds[d->pos++];
  memset(&d->ent, 0, sizeof(d->ent));
  d->ent.d_ino = (ino_t)(fd + 1);
  d->ent.d_type = DT_LNK;  // proc fd entries are magic symlinks
  snprintf(d->ent.d_name, sizeof(d->ent.d_name), "%ld", fd);
  return &d->ent;
}

struct dirent64* readdir64(DIR* dp) {
  static auto real_readdir64 =
      (struct dirent64 * (*)(DIR*)) dlsym(RTLD_NEXT, "readdir64");
  VirtFdDir* d = vdir_of(dp);
  if (!d) return real_readdir64(dp);
  // x86_64 glibc: dirent and dirent64 share the layout
  return (struct dirent64*)readdir(dp);
}

int closedir(DIR* dp) {
  static auto real_closedir = (int (*)(DIR*))dlsym(RTLD_NEXT, "closedir");
  VirtFdDir* d = vdir_of(dp);
  if (!d) return real_closedir(dp);
  pthread_mutex_lock(&g_vdir_mu);
  for (auto& slot : g_vdirs)
    if (slot.load(std::memory_order_relaxed) == d)
      slot.store(nullptr, std::memory_order_release);
  pthread_mutex_unlock(&g_vdir_mu);
  if (d->backing_fd >= 0) sys_native(SYS_close, d->backing_fd);
  free(d);
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// seccomp/SIGSYS backstop (reference analog: shim.c:399-463): raw syscall
// instructions that bypass the interposed libc symbols trap to SIGSYS and
// are routed through the same wrappers. Only the emulated syscall numbers
// trap; everything else — and anything issued from the gate — is allowed.
// ---------------------------------------------------------------------------

namespace {

// libc-convention wrapper result → raw-kernel convention (-errno)
#define RAWRET(call)                        \
  ({                                        \
    long _r = (long)(call);                 \
    _r < 0 ? -(long)errno : _r;             \
  })

// /proc/self/fd/<n> for a MANAGED n: reopening one's own descriptor is a
// dup of the open description (the kernel's magic-symlink semantics for
// pipes/sockets reduce to that here). Returns LONG_MIN when the path is
// not a managed /proc/self/fd entry (caller falls through to native).
long virt_proc_fd_open(const char* path) {
  if (!path) return LONG_MIN;
  const char* num = nullptr;
  if (strncmp(path, "/proc/self/fd/", 14) == 0)
    num = path + 14;
  else if (strncmp(path, "/dev/fd/", 8) == 0)  // portable alias
    num = path + 8;
  else
    return LONG_MIN;
  char* end = nullptr;
  long n = strtol(num, &end, 10);
  if (!end || *end != 0 || n < FD_BASE) return LONG_MIN;
  return RAWRET(dup((int)n));
}

long route_raw_syscall(long nr, long a0, long a1, long a2, long a3, long a4,
                       long a5) {
  switch (nr) {
    case SYS_socket:
      return RAWRET(socket((int)a0, (int)a1, (int)a2));
    case SYS_bind:
      return RAWRET(bind((int)a0, (const struct sockaddr*)a1, (socklen_t)a2));
    case SYS_listen:
      return RAWRET(listen((int)a0, (int)a1));
    case SYS_connect:
      return RAWRET(
          connect((int)a0, (const struct sockaddr*)a1, (socklen_t)a2));
    case SYS_accept:
      return RAWRET(
          accept4((int)a0, (struct sockaddr*)a1, (socklen_t*)a2, 0));
    case SYS_accept4:
      return RAWRET(
          accept4((int)a0, (struct sockaddr*)a1, (socklen_t*)a2, (int)a3));
    case SYS_sendto:
      return RAWRET(sendto((int)a0, (const void*)a1, (size_t)a2, (int)a3,
                           (const struct sockaddr*)a4, (socklen_t)a5));
    case SYS_recvfrom:
      return RAWRET(recvfrom((int)a0, (void*)a1, (size_t)a2, (int)a3,
                             (struct sockaddr*)a4, (socklen_t*)a5));
    case SYS_sendmsg:
      return RAWRET(sendmsg((int)a0, (const struct msghdr*)a1, (int)a2));
    case SYS_recvmsg:
      return RAWRET(recvmsg((int)a0, (struct msghdr*)a1, (int)a2));
    case SYS_shutdown:
      return RAWRET(shutdown((int)a0, (int)a1));
    case SYS_getsockname:
      return RAWRET(
          getsockname((int)a0, (struct sockaddr*)a1, (socklen_t*)a2));
    case SYS_getpeername:
      return RAWRET(
          getpeername((int)a0, (struct sockaddr*)a1, (socklen_t*)a2));
    case SYS_setsockopt:
      return RAWRET(setsockopt((int)a0, (int)a1, (int)a2, (const void*)a3,
                               (socklen_t)a4));
    case SYS_getsockopt:
      return RAWRET(
          getsockopt((int)a0, (int)a1, (int)a2, (void*)a3, (socklen_t*)a4));
    case SYS_read:
      return RAWRET(read((int)a0, (void*)a1, (size_t)a2));
    case SYS_write:
      return RAWRET(write((int)a0, (const void*)a1, (size_t)a2));
    case SYS_readv:
      return RAWRET(readv((int)a0, (const struct iovec*)a1, (int)a2));
    case SYS_writev:
      return RAWRET(writev((int)a0, (const struct iovec*)a1, (int)a2));
    case SYS_close:
      return RAWRET(close((int)a0));
    case SYS_dup:
      return RAWRET(dup((int)a0));
    case SYS_dup2:
      return RAWRET(dup2((int)a0, (int)a1));
    case SYS_dup3:
      return RAWRET(dup3((int)a0, (int)a1, (int)a2));
    case SYS_fcntl:
      return RAWRET(fcntl((int)a0, (int)a1, a2));
    case SYS_ioctl:
      return RAWRET(ioctl((int)a0, (unsigned long)a1, (void*)a2));
    case SYS_pipe: {
      return RAWRET(pipe2((int*)a0, 0));
    }
    case SYS_pipe2:
      return RAWRET(pipe2((int*)a0, (int)a1));
    case SYS_eventfd:
      return RAWRET(eventfd((unsigned int)a0, 0));
    case SYS_eventfd2:
      return RAWRET(eventfd((unsigned int)a0, (int)a1));
    case SYS_timerfd_create:
      return RAWRET(timerfd_create((int)a0, (int)a1));
    case SYS_timerfd_settime:
      return RAWRET(timerfd_settime((int)a0, (int)a1,
                                    (const struct itimerspec*)a2,
                                    (struct itimerspec*)a3));
    case SYS_timerfd_gettime:
      return RAWRET(timerfd_gettime((int)a0, (struct itimerspec*)a1));
    case SYS_epoll_create:
    case SYS_epoll_create1:
      return RAWRET(epoll_create1(nr == SYS_epoll_create ? 0 : (int)a0));
    case SYS_epoll_ctl:
      return RAWRET(
          epoll_ctl((int)a0, (int)a1, (int)a2, (struct epoll_event*)a3));
    case SYS_epoll_wait:
      return RAWRET(
          epoll_wait((int)a0, (struct epoll_event*)a1, (int)a2, (int)a3));
    case SYS_epoll_pwait:
      return RAWRET(epoll_pwait((int)a0, (struct epoll_event*)a1, (int)a2,
                                (int)a3, (const sigset_t*)a4));
    case SYS_poll:
      return RAWRET(poll((struct pollfd*)a0, (nfds_t)a1, (int)a2));
    case SYS_ppoll:
      return RAWRET(ppoll((struct pollfd*)a0, (nfds_t)a1,
                          (const struct timespec*)a2,
                          (const sigset_t*)a3));
    case SYS_signalfd:
      return RAWRET(signalfd((int)a0, (const sigset_t*)a1, 0));
    case SYS_signalfd4:
      return RAWRET(signalfd((int)a0, (const sigset_t*)a1, (int)a3));
    case SYS_getrlimit:
      return RAWRET(getrlimit((int)a0, (struct rlimit*)a1));
    case SYS_setrlimit:
      return RAWRET(setrlimit((int)a0, (const struct rlimit*)a1));
    case SYS_prlimit64:
      return RAWRET(prlimit((pid_t)a0, (__rlimit_resource)a1,
                            (const struct rlimit*)a2, (struct rlimit*)a3));
    case SYS_getrusage:
      return RAWRET(getrusage((int)a0, (struct rusage*)a1));
    case SYS_select:
      return RAWRET(select((int)a0, (fd_set*)a1, (fd_set*)a2, (fd_set*)a3,
                           (struct timeval*)a4));
    case SYS_pselect6: {
      // the kernel ABI's 6th arg is {const sigset_t*, size_t}
      struct KernelSigset {
        const sigset_t* ss;
        size_t len;
      };
      const KernelSigset* sm = (const KernelSigset*)a5;
      return RAWRET(pselect((int)a0, (fd_set*)a1, (fd_set*)a2, (fd_set*)a3,
                            (const struct timespec*)a4,
                            sm ? sm->ss : nullptr));
    }
    case SYS_clock_gettime:
      return RAWRET(clock_gettime((clockid_t)a0, (struct timespec*)a1));
    case SYS_gettimeofday:
      return RAWRET(gettimeofday((struct timeval*)a0, (void*)a1));
    case SYS_time: {
      time_t t = time((time_t*)a0);
      return (long)t;
    }
    case SYS_nanosleep:
      return RAWRET(
          nanosleep((const struct timespec*)a0, (struct timespec*)a1));
    case SYS_clock_nanosleep: {
      int e = clock_nanosleep((clockid_t)a0, (int)a1,
                              (const struct timespec*)a2,
                              (struct timespec*)a3);
      return -(long)e;  // clock_nanosleep returns the errno directly
    }
    case SYS_getrandom:
      return RAWRET(getrandom((void*)a0, (size_t)a1, (unsigned int)a2));
    case SYS_sched_getaffinity:
      if (!g_ch) return shim_gate_syscall(nr, a0, a1, a2, a3, a4, a5);
      return sched_getaffinity_raw((pid_t)a0, (size_t)a1, (cpu_set_t*)a2);
    case SYS_fstat:
      return RAWRET(fstat((int)a0, (struct stat*)a1));
    case SYS_mmap: {
      void* r = mmap((void*)a0, (size_t)a1, (int)a2, (int)a3, (int)a4,
                     (off_t)a5);
      return r == MAP_FAILED ? -(long)errno : (long)r;
    }
    case SYS_newfstatat:
      return RAWRET(fstatat((int)a0, (const char*)a1, (struct stat*)a2,
                            (int)a3));
    case SYS_statx:
      return RAWRET(statx((int)a0, (const char*)a1, (int)a2,
                          (unsigned int)a3, (struct statx*)a4));
    case SYS_open: {
      long vfd = virt_cpu_file_open((const char*)a0);
      if (vfd >= 0) return vfd;
      vfd = virt_proc_fd_open((const char*)a0);
      if (vfd != LONG_MIN) return vfd;
      return shim_gate_syscall(nr, a0, a1, a2, a3, a4, a5);
    }
    case SYS_openat: {
      const char* p = (const char*)a1;
      if (p && p[0] == '/') {
        long vfd = virt_cpu_file_open(p);
        if (vfd >= 0) return vfd;
        vfd = virt_proc_fd_open(p);
        if (vfd != LONG_MIN) return vfd;
      }
      return shim_gate_syscall(nr, a0, a1, a2, a3, a4, a5);
    }
    default:
      return shim_gate_syscall(nr, a0, a1, a2, a3, a4, a5);
  }
}

void on_sigsys(int sig, siginfo_t* info, void* vctx) {
  (void)sig;
#if defined(__x86_64__)
  ucontext_t* uc = (ucontext_t*)vctx;
  greg_t* g = uc->uc_mcontext.gregs;
  long nr = (long)info->si_syscall;
  // Recursion guard for exec'd images: an INHERITED filter from the
  // pre-exec image traps even this image's gate (different address), so a
  // native-fallback path would re-trap forever. Depth >= 2 on the same
  // thread means exactly that — fail the syscall loudly instead.
  static __thread int depth = 0;
  if (depth >= 2) {
    g[REG_RAX] = (greg_t)(-ENOSYS);
    return;
  }
  depth++;
  long r = route_raw_syscall(nr, g[REG_RDI], g[REG_RSI], g[REG_RDX],
                             g[REG_R10], g[REG_R8], g[REG_R9]);
  depth--;
  g[REG_RAX] = (greg_t)r;
#else
  (void)info;
  (void)vctx;
#endif
}

// syscall numbers the backstop traps (the emulated surface; everything
// else — memory, threads, files, process control — passes through)
// Trap classification. FD0/FD01 syscalls trap ONLY when the fd argument
// is in the emulated range (>= FD_BASE): low/real-fd operations run native
// with zero filter cost, and — crucially — an EXEC'D image (which inherits
// this filter but starts with no SIGSYS handler until its own shim
// constructor runs) can boot: ld.so/libc startup only touches low fds.
enum TrapAct { ACT_TRAP, ACT_FD0, ACT_FD01 };
struct TrapEntry {
  int nr;
  TrapAct act;
};
const TrapEntry kTrapped[] = {
    {SYS_read, ACT_FD0},          {SYS_write, ACT_FD0},
    {SYS_close, ACT_FD0},         {SYS_poll, ACT_TRAP},
    {SYS_ioctl, ACT_FD0},         {SYS_readv, ACT_FD0},
    {SYS_writev, ACT_FD0},        {SYS_select, ACT_TRAP},
    {SYS_dup, ACT_FD0},           {SYS_dup2, ACT_FD01},
    {SYS_dup3, ACT_FD01},         {SYS_nanosleep, ACT_TRAP},
    {SYS_socket, ACT_TRAP},       {SYS_connect, ACT_FD0},
    {SYS_accept, ACT_FD0},        {SYS_accept4, ACT_FD0},
    {SYS_sendto, ACT_FD0},        {SYS_recvfrom, ACT_FD0},
    {SYS_sendmsg, ACT_FD0},       {SYS_recvmsg, ACT_FD0},
    {SYS_shutdown, ACT_FD0},      {SYS_bind, ACT_FD0},
    {SYS_listen, ACT_FD0},        {SYS_getsockname, ACT_FD0},
    {SYS_getpeername, ACT_FD0},   {SYS_setsockopt, ACT_FD0},
    {SYS_getsockopt, ACT_FD0},    {SYS_fcntl, ACT_FD0},
    {SYS_gettimeofday, ACT_TRAP}, {SYS_time, ACT_TRAP},
    {SYS_clock_gettime, ACT_TRAP}, {SYS_clock_nanosleep, ACT_TRAP},
    {SYS_epoll_create, ACT_TRAP}, {SYS_epoll_create1, ACT_TRAP},
    {SYS_epoll_ctl, ACT_FD0},     {SYS_epoll_wait, ACT_FD0},
    {SYS_epoll_pwait, ACT_FD0},   {SYS_timerfd_create, ACT_TRAP},
    {SYS_timerfd_settime, ACT_FD0}, {SYS_timerfd_gettime, ACT_FD0},
    {SYS_eventfd, ACT_TRAP},      {SYS_eventfd2, ACT_TRAP},
    {SYS_pipe, ACT_TRAP},         {SYS_pipe2, ACT_TRAP},
    {SYS_getrandom, ACT_TRAP},    {SYS_pselect6, ACT_TRAP},
    {SYS_sched_getaffinity, ACT_TRAP},
    // signal-plane descriptors + mask-swapping waits ride the virtual
    // signal tables; resource limits/usage are deterministic synthesis
    {SYS_signalfd, ACT_TRAP},     {SYS_signalfd4, ACT_TRAP},
    {SYS_ppoll, ACT_TRAP},
    {SYS_getrlimit, ACT_TRAP},    {SYS_setrlimit, ACT_TRAP},
    {SYS_prlimit64, ACT_TRAP},    {SYS_getrusage, ACT_TRAP},
    // opens trap so CPU-count pseudo-files virtualize even through
    // glibc-internal (non-PLT) calls; non-matching paths re-enter the
    // kernel through the gate — one SIGSYS round trip per open
    {SYS_open, ACT_TRAP},         {SYS_openat, ACT_TRAP},
    // stat family: managed fds present synthesized metadata (PSYS_FSTAT);
    // newfstatat discriminates on dirfd (AT_EMPTY_PATH fstat form)
    {SYS_fstat, ACT_FD0},         {SYS_newfstatat, ACT_FD0},
    {SYS_statx, ACT_FD0},
    // mmap policy (writable file-backed MAP_SHARED refused) must hold
    // for raw/glibc-internal calls too; the shim's own channel maps go
    // through the gate and are exempt
    {SYS_mmap, ACT_TRAP},
};

}  // namespace

// ---------------------------------------------------------------------------
// threads, futexes, fork (reference analogs: thread_preload.c:358-400 clone
// bootstrap, futex.c/syscall/futex.c, process.c:460-531). Execution model:
// the driver runs AT MOST ONE thread of a process between syscalls (it
// withholds wake replies until the running thread blocks), which makes
// multithreaded apps deterministic. Blocking synchronization therefore must
// never block NATIVELY (a native futex wait would wedge the whole process):
// the pthread mutex/cond surface is interposed here and parks threads in
// the DRIVER, keyed by futex word address. The shim reads/writes the words
// directly — same address space, no remote memory manager needed.
// ---------------------------------------------------------------------------

namespace {

Channel* map_channel(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  // raw syscall: the libc-visible mmap wrapper (below) denies writable
  // MAP_SHARED file mappings as policy, and must not deny our own channels
  void* p = (void*)sys_native(SYS_mmap, (long)nullptr, sizeof(Channel),
                              PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (p == MAP_FAILED || ((Channel*)p)->magic != IPC_MAGIC) return nullptr;
  return (Channel*)p;
}

// Registered via on_exit in shim_init: catches BOTH explicit exit() and
// return-from-main (glibc calls exit internally, bypassing any interposed
// exit symbol). The driver needs this DETERMINISTIC, sim-time-stamped
// process-done signal — fork children have no popen handle to poll, and a
// parent parked in waitpid must wake at a well-defined virtual instant.
// (_exit/_Exit bypass atexit and so skip this — the driver's STOP path
// uses _exit precisely to avoid a re-entrant notification.)
void shim_notify_exit(int status, void*) {
  if (!g_ch) return;
  int64_t a[6] = {status, 1 /* process-level */, 0, 0, 0, 0};
  ipc_call(PSYS_THREAD_EXIT, a, nullptr, 0, nullptr, 0, nullptr);
  g_ch = nullptr;
  t_ch = nullptr;
}

int futex_wait_driver(const void* uaddr, int64_t timeout_ns) {
  int64_t a[6] = {(int64_t)(uintptr_t)uaddr, timeout_ns, 0, 0, 0, 0};
  int64_t r = ipc_call(PSYS_FUTEX_WAIT, a, nullptr, 0, nullptr, 0, nullptr);
  return r < 0 ? (int)errno : 0;
}

void futex_wake_driver(const void* uaddr, int n) {
  int64_t a[6] = {(int64_t)(uintptr_t)uaddr, n, 0, 0, 0, 0};
  ipc_call(PSYS_FUTEX_WAKE, a, nullptr, 0, nullptr, 0, nullptr);
}

struct ThreadReg {
  ThreadReg* next;
  pthread_t handle;
  Channel* ch;
  void* (*fn)(void*);
  void* arg;
  char shm[160];
  std::atomic<int> done;
};
ThreadReg* g_threads = nullptr;
std::atomic_flag g_threads_lock = ATOMIC_FLAG_INIT;
__thread ThreadReg* t_reg = nullptr;

void thread_epilogue() {
  // done-flag + joiner wake + driver notification; runs exactly once per
  // managed thread, whether it returns from its start routine or calls
  // pthread_exit (which is interposed to come through here)
  ThreadReg* r = t_reg;
  if (!r) return;
  t_reg = nullptr;
  r->done.store(1, std::memory_order_release);
  futex_wake_driver(&r->done, INT32_MAX);  // joiners
  int64_t a[6] = {0, 0, 0, 0, 0, 0};
  ipc_call(PSYS_THREAD_EXIT, a, nullptr, 0, nullptr, 0, nullptr);
  t_ch = nullptr;
}

void* thread_tramp(void* vp) {
#if defined(__x86_64__)
  // PR_SET_TSC is per-thread: new threads must trap rdtsc too (only if
  // the process-wide SIGSEGV emulator is actually installed)
  if (g_tsc_trap_on) prctl(PR_SET_TSC, PR_TSC_SIGSEGV, 0, 0, 0);
#endif
  ThreadReg* r = (ThreadReg*)vp;
  Channel* ch = map_channel(r->shm);
  if (ch) {
    t_ch = ch;
    r->ch = ch;
    ch->shim_pid = (int32_t)sys_native(SYS_gettid);
    // HELLO on the thread's own channel; the driver admits this thread
    // (replies) only once the spawner blocks — one-at-a-time execution
    ch->type = MSG_HELLO;
    ch->ret = ch->shim_pid;
    ch->data_len = 0;
    sem_post(&ch->to_driver);
    sem_wait_spinning(&ch->to_shim, g_spin);
  } else {
    SHIM_LOG("thread channel %s failed to map; thread runs unmanaged",
             r->shm);
  }
  t_reg = r;
  void* rv = r->fn(r->arg);
  thread_epilogue();
  return rv;
}

ThreadReg* find_thread(pthread_t h) {
  raw_lock(&g_threads_lock);
  ThreadReg* r = g_threads;
  while (r && !pthread_equal(r->handle, h)) r = r->next;
  raw_unlock(&g_threads_lock);
  return r;
}

// glibc struct __pthread_mutex_s prefix (x86-64): the interposed mutex
// surface owns the semantics, reusing the same fields
struct MutexView {
  int lock;        // futex word: 0 free, 1 locked, 2 locked+waiters
  unsigned count;  // recursion count
  int owner;       // tid
  unsigned nusers;
  int kind;        // PTHREAD_MUTEX_* from pthread_mutex_init (glibc's)
};

int my_tid() {
  static __thread int tid = 0;
  if (!tid) tid = (int)sys_native(SYS_gettid);
  return tid;
}

}  // namespace

extern "C" {

int pthread_create(pthread_t* out, const pthread_attr_t* attr,
                   void* (*fn)(void*), void* arg) {
  static auto real = (int (*)(pthread_t*, const pthread_attr_t*,
                              void* (*)(void*), void*))
      dlsym(RTLD_NEXT, "pthread_create");
  if (!g_ch) return real(out, attr, fn, arg);
  ThreadReg* r = (ThreadReg*)calloc(1, sizeof(ThreadReg));
  r->fn = fn;
  r->arg = arg;
  uint32_t out_len = 0;
  int64_t a[6] = {0, 0, 0, 0, 0, 0};
  int64_t rc = ipc_call(PSYS_THREAD_NEW, a, nullptr, 0, r->shm,
                        sizeof(r->shm) - 1, &out_len);
  if (rc < 0) {
    free(r);
    return EAGAIN;
  }
  r->shm[out_len < sizeof(r->shm) - 1 ? out_len : sizeof(r->shm) - 1] = 0;
  int ret = real(out, attr, thread_tramp, r);
  if (ret != 0) {
    free(r);  // driver-side channel leaks until process end; harmless
    return ret;
  }
  r->handle = *out;
  raw_lock(&g_threads_lock);
  r->next = g_threads;
  g_threads = r;
  raw_unlock(&g_threads_lock);
  return 0;
}

void pthread_exit(void* retval) {
  static auto real = (void (*)(void*))dlsym(RTLD_NEXT, "pthread_exit");
  thread_epilogue();  // no-op for unmanaged/main threads (t_reg unset)
  real(retval);
  raw_exit(0);  // not reached; placates noreturn
}

int pthread_join(pthread_t th, void** retval) {
  static auto real = (int (*)(pthread_t, void**))
      dlsym(RTLD_NEXT, "pthread_join");
  ThreadReg* r = g_ch ? find_thread(th) : nullptr;
  if (!r) return real(th, retval);
  // park in the driver until the trampoline flips done (the native join
  // below then returns ~immediately — the thread has left app code)
  while (r->done.load(std::memory_order_acquire) == 0)
    futex_wait_driver(&r->done, -1);
  int ret = real(th, retval);
  raw_lock(&g_threads_lock);
  ThreadReg** pp = &g_threads;
  while (*pp && *pp != r) pp = &(*pp)->next;
  if (*pp) *pp = r->next;
  raw_unlock(&g_threads_lock);
  free(r);
  return ret;
}

int pthread_mutex_lock(pthread_mutex_t* m) {
  static auto real = (int (*)(pthread_mutex_t*))
      dlsym(RTLD_NEXT, "pthread_mutex_lock");
  if (!g_ch) return real(m);
  MutexView* v = (MutexView*)m;
  int tid = my_tid();
  if ((v->kind & 3) == PTHREAD_MUTEX_RECURSIVE && v->owner == tid) {
    v->count++;
    return 0;
  }
  auto* w = (std::atomic<int>*)&v->lock;
  int expected = 0;
  if (!w->compare_exchange_strong(expected, 1)) {
    // contended: classic two-state futex mutex, waits parked in-driver
    while (w->exchange(2) != 0) futex_wait_driver(w, -1);
  }
  v->owner = tid;
  v->count = 1;
  return 0;
}

int pthread_mutex_trylock(pthread_mutex_t* m) {
  static auto real = (int (*)(pthread_mutex_t*))
      dlsym(RTLD_NEXT, "pthread_mutex_trylock");
  if (!g_ch) return real(m);
  MutexView* v = (MutexView*)m;
  int tid = my_tid();
  if ((v->kind & 3) == PTHREAD_MUTEX_RECURSIVE && v->owner == tid) {
    v->count++;
    return 0;
  }
  auto* w = (std::atomic<int>*)&v->lock;
  int expected = 0;
  if (w->compare_exchange_strong(expected, 1)) {
    v->owner = tid;
    v->count = 1;
    return 0;
  }
  return EBUSY;
}

int pthread_mutex_unlock(pthread_mutex_t* m) {
  static auto real = (int (*)(pthread_mutex_t*))
      dlsym(RTLD_NEXT, "pthread_mutex_unlock");
  if (!g_ch) return real(m);
  MutexView* v = (MutexView*)m;
  if ((v->kind & 3) == PTHREAD_MUTEX_RECURSIVE && v->count > 1) {
    v->count--;
    return 0;
  }
  v->owner = 0;
  v->count = 0;
  auto* w = (std::atomic<int>*)&v->lock;
  if (w->exchange(0) == 2) futex_wake_driver(w, 1);
  return 0;
}

// Condition variables: our representation is a bare sequence counter in
// the (zero-initialized) pthread_cond_t; wait parks in the driver until a
// signal/broadcast bumps the sequence. The driver's one-at-a-time
// scheduling means check-then-park has no lost-wakeup race: the potential
// waker cannot run between our sequence read and our park.
int pthread_cond_wait(pthread_cond_t* c, pthread_mutex_t* m) {
  static auto real = (int (*)(pthread_cond_t*, pthread_mutex_t*))
      dlsym(RTLD_NEXT, "pthread_cond_wait");
  if (!g_ch) return real(c, m);
  auto* seq = (std::atomic<unsigned>*)c;
  unsigned s = seq->load(std::memory_order_acquire);
  pthread_mutex_unlock(m);
  while (seq->load(std::memory_order_acquire) == s)
    futex_wait_driver(seq, -1);
  pthread_mutex_lock(m);
  return 0;
}

int pthread_cond_timedwait(pthread_cond_t* c, pthread_mutex_t* m,
                           const struct timespec* abstime) {
  static auto real = (int (*)(pthread_cond_t*, pthread_mutex_t*,
                              const struct timespec*))
      dlsym(RTLD_NEXT, "pthread_cond_timedwait");
  if (!g_ch) return real(c, m, abstime);
  auto* seq = (std::atomic<unsigned>*)c;
  unsigned s = seq->load(std::memory_order_acquire);
  pthread_mutex_unlock(m);
  int err = 0;
  while (seq->load(std::memory_order_acquire) == s) {
    // remaining virtual time until the absolute (sim-clock) deadline
    int64_t now = ipc_call6(SYS_clock_gettime, CLOCK_REALTIME);
    int64_t dl =
        (int64_t)abstime->tv_sec * 1000000000LL + abstime->tv_nsec;
    if (now >= dl) {
      err = ETIMEDOUT;
      break;
    }
    if (futex_wait_driver(seq, dl - now) == ETIMEDOUT &&
        seq->load(std::memory_order_acquire) == s) {
      err = ETIMEDOUT;
      break;
    }
  }
  pthread_mutex_lock(m);
  return err;
}

int pthread_cond_signal(pthread_cond_t* c) {
  static auto real = (int (*)(pthread_cond_t*))
      dlsym(RTLD_NEXT, "pthread_cond_signal");
  if (!g_ch) return real(c);
  auto* seq = (std::atomic<unsigned>*)c;
  seq->fetch_add(1, std::memory_order_acq_rel);
  futex_wake_driver(seq, 1);
  return 0;
}

int pthread_cond_broadcast(pthread_cond_t* c) {
  static auto real = (int (*)(pthread_cond_t*))
      dlsym(RTLD_NEXT, "pthread_cond_broadcast");
  if (!g_ch) return real(c);
  auto* seq = (std::atomic<unsigned>*)c;
  seq->fetch_add(1, std::memory_order_acq_rel);
  futex_wake_driver(seq, INT32_MAX);
  return 0;
}

pid_t fork(void) {
  static auto real = (pid_t (*)(void))dlsym(RTLD_NEXT, "fork");
  if (!g_ch) return real();
  char shm[160] = {0};
  uint32_t out_len = 0;
  int64_t a[6] = {0, 0, 0, 0, 0, 0};
  int64_t rc = ipc_call(PSYS_FORK, a, nullptr, 0, shm, sizeof(shm) - 1,
                        &out_len);
  if (rc < 0) {
    errno = EAGAIN;
    return -1;
  }
  shm[out_len < sizeof(shm) - 1 ? out_len : sizeof(shm) - 1] = 0;
  pid_t p = real();
  if (p < 0) {
    // native fork failed AFTER the driver registered a child: retract it
    // (a[1]=2) or the driver would wait forever for its HELLO
    int saved = errno;
    int64_t r2[6] = {0, 2, 0, 0, 0, 0};
    ipc_call(PSYS_THREAD_EXIT, r2, nullptr, 0, nullptr, 0, nullptr);
    errno = saved;
    return -1;
  }
  if (p == 0) {
    // child: single-threaded; adopt the pre-created channel (the parent's
    // mapping is inherited but belongs to the parent)
    Channel* ch = map_channel(shm);
    if (!ch) raw_exit(127);
    g_ch = ch;
    t_ch = ch;
    g_threads = nullptr;
    // a later execve must hand the CHILD's channel to the fresh image,
    // not the inherited parent path
    setenv(ENV_SHM, shm, 1);
    ch->shim_pid = getpid();
    ch->type = MSG_HELLO;
    ch->ret = getpid();
    ch->data_len = 0;
    sem_post(&ch->to_driver);
    sem_wait_spinning(&ch->to_shim, g_spin);
  }
  return p;
}

// _exit/_Exit bypass atexit/on_exit, so without interposition the driver
// would never learn the process ended (fork children have no popen handle
// to poll — they would read as wedged). Notify first, then raw-exit.
void _exit(int status) {
  if (g_ch) shim_notify_exit(status, nullptr);
  raw_exit(status);
}

void _Exit(int status) { _exit(status); }

pid_t waitpid(pid_t pid, int* wstatus, int options) {
  static auto real = (pid_t (*)(pid_t, int*, int))
      dlsym(RTLD_NEXT, "waitpid");
  if (!g_ch) return real(pid, wstatus, options);
  // Fully driver-emulated for managed fork children: the driver knows the
  // child's (deterministic, sim-time-stamped) exit and parks us until
  // then — never block natively, which would wedge the whole process.
  // WNOHANG also goes through the driver (args[1]=1): polling the NATIVE
  // child state would leak wall-clock timing into the simulation.
  int64_t a[6] = {pid, (options & WNOHANG) ? 1 : 0, 0, 0, 0, 0};
  int32_t status = 0;
  uint32_t out_len = 0;
  int64_t rc = ipc_call(PSYS_WAITPID, a, nullptr, 0, &status,
                        sizeof(status), &out_len);
  if (rc < 0) return -1;  // errno set (ECHILD)
  if (rc == 0) return 0;  // WNOHANG: no managed child done yet
  // the driver composes the full wait-status word (normal exit OR
  // signaled — see driver._wait_status); pass it through verbatim
  if (wstatus) *wstatus = status;
  real((pid_t)rc, nullptr, WNOHANG);  // opportunistic zombie reap
  return (pid_t)rc;
}

pid_t wait(int* wstatus) { return waitpid(-1, wstatus, 0); }

extern char** environ;

int execv(const char* path, char* const argv[]) {
  // glibc's execv calls execve internally (not via the PLT), so interpose
  // it explicitly and funnel into the managed execve below
  return execve(path, argv, environ);
}

int execve(const char* path, char* const argv[], char* const envp[]) {
  static auto real = (int (*)(const char*, char* const[], char* const[]))
      dlsym(RTLD_NEXT, "execve");
  if (!g_ch) return real(path, argv, envp);
  // The driver RESPAWNS the image as a fresh managed process (clean
  // seccomp state, same virtual identity) and this process exits — see
  // PSYS_EXEC in ipc.h for why native execve cannot work here. Wire
  // format: path NUL, then the FULL argv (argv[0] included — multicall
  // binaries dispatch on it) as NUL-terminated strings, then envp; argc
  // rides in args[0] so empty argv strings cannot confuse the framing.
  char buf[IPC_DATA_MAX];
  uint32_t off = 0;
  auto put = [&](const char* s) {
    size_t len = strlen(s) + 1;
    if (off + len > sizeof(buf)) return false;
    memcpy(buf + off, s, len);
    off += (uint32_t)len;
    return true;
  };
  if (!put(path)) {
    errno = E2BIG;
    return -1;
  }
  int64_t argc = 0;
  for (int j = 0; argv && argv[j]; j++, argc++)
    if (!put(argv[j])) {
      errno = E2BIG;
      return -1;
    }
  for (int j = 0; envp && envp[j]; j++)
    if (!put(envp[j])) {
      errno = E2BIG;
      return -1;
    }
  int64_t a[6] = {argc, 0, 0, 0, 0, 0};
  int64_t rc = ipc_call(PSYS_EXEC, a, buf, off, nullptr, 0, nullptr);
  if (rc < 0) return -1;  // errno set (e.g. ENOENT)
  raw_exit(0);  // replaced by the respawned image; never returns
}

}  // extern "C"

namespace {

// ---------------------------------------------------------------------------
// vDSO neutralization. The vDSO serves clock_gettime/gettimeofday/time as
// plain userspace reads of kernel-exported data — no kernel entry, so the
// seccomp backstop below never sees them, and a statically-linked binary's
// libc would read real wall-clock time, silently breaking determinism
// (ADVICE r1). Fix: locate the vDSO's exported time symbols and overwrite
// each entry point with `mov eax, <nr>; syscall; ret`. The syscall
// instruction now lives OUTSIDE the shim gate window, so the BPF traps it
// and the SIGSYS handler routes it to the emulated clock. Writes go through
// /proc/self/mem, whose FOLL_FORCE semantics bypass the vDSO VMA's write
// protection (the same trick rr uses for its vDSO monkeypatching).
// ---------------------------------------------------------------------------

struct VdsoTarget {
  const char* name;
  uint32_t nr;
};

void shim_patch_vdso() {
#if defined(__x86_64__)
  const char* opt = getenv(ENV_VDSO);
  if (opt && strcmp(opt, "0") == 0) return;
  uintptr_t base = (uintptr_t)getauxval(AT_SYSINFO_EHDR);
  if (!base) return;  // no vDSO mapped: nothing to neutralize
  const Elf64_Ehdr* eh = (const Elf64_Ehdr*)base;
  if (memcmp(eh->e_ident, ELFMAG, SELFMAG) != 0) {
    SHIM_LOG("vdso: bad ELF magic; time determinism gap remains");
    return;
  }
  const Elf64_Phdr* ph = (const Elf64_Phdr*)(base + eh->e_phoff);
  uintptr_t dyn_vaddr = 0;
  uintptr_t load_vaddr = UINTPTR_MAX;
  for (int i = 0; i < eh->e_phnum; i++) {
    if (ph[i].p_type == PT_DYNAMIC) dyn_vaddr = ph[i].p_vaddr;
    if (ph[i].p_type == PT_LOAD && ph[i].p_vaddr < load_vaddr)
      load_vaddr = ph[i].p_vaddr;
  }
  if (!dyn_vaddr || load_vaddr == UINTPTR_MAX) {
    SHIM_LOG("vdso: no PT_DYNAMIC/PT_LOAD; gap remains");
    return;
  }
  uintptr_t slide = base - load_vaddr;
  const Elf64_Sym* symtab = nullptr;
  const char* strtab = nullptr;
  for (const Elf64_Dyn* d = (const Elf64_Dyn*)(slide + dyn_vaddr);
       d->d_tag != DT_NULL; d++) {
    uintptr_t p = (uintptr_t)d->d_un.d_ptr;
    if (p < base) p += slide;  // vDSO d_ptr values are usually unrelocated
    if (d->d_tag == DT_SYMTAB) symtab = (const Elf64_Sym*)p;
    if (d->d_tag == DT_STRTAB) strtab = (const char*)p;
  }
  if (!symtab || !strtab || (uintptr_t)strtab <= (uintptr_t)symtab) {
    SHIM_LOG("vdso: no dynsym/dynstr; gap remains");
    return;
  }
  // .dynsym is immediately followed by .dynstr in the vDSO image; the gap
  // between them bounds the symbol count (standard in-memory ELF trick —
  // there is no reliable DT_HASH on all kernels).
  size_t nsyms =
      ((uintptr_t)strtab - (uintptr_t)symtab) / sizeof(Elf64_Sym);
  if (nsyms == 0 || nsyms > 4096) {
    SHIM_LOG("vdso: implausible symbol count %zu; gap remains", nsyms);
    return;
  }
  const VdsoTarget targets[] = {
      {"__vdso_clock_gettime", SYS_clock_gettime},
      {"__vdso_gettimeofday", SYS_gettimeofday},
      {"__vdso_time", SYS_time},
      {"clock_gettime", SYS_clock_gettime},
      {"gettimeofday", SYS_gettimeofday},
      {"time", SYS_time},
  };
  int memfd = (int)sys_native(SYS_open, "/proc/self/mem", O_RDWR, 0);
  if (memfd < 0) {
    SHIM_LOG("vdso: open /proc/self/mem failed: %s; gap remains",
             strerror(errno));
    return;
  }
  int patched = 0, failed = 0;
  // Track patched addresses: aliased names (clock_gettime aliases
  // __vdso_clock_gettime) share one entry point — patch once.
  uintptr_t done[sizeof(targets) / sizeof(targets[0])] = {0};
  for (size_t s = 0; s < nsyms; s++) {
    const Elf64_Sym* sym = &symtab[s];
    if (sym->st_value == 0 || sym->st_name == 0) continue;
    const char* nm = strtab + sym->st_name;
    for (size_t t = 0; t < sizeof(targets) / sizeof(targets[0]); t++) {
      if (strcmp(nm, targets[t].name) != 0) continue;
      uintptr_t addr = slide + sym->st_value;
      bool seen = false;
      for (uintptr_t a : done) seen |= (a == addr);
      if (seen) break;
      uint32_t nr = targets[t].nr;
      // mov eax, imm32; syscall; ret
      uint8_t stub[8] = {0xb8, (uint8_t)nr, (uint8_t)(nr >> 8),
                         (uint8_t)(nr >> 16), (uint8_t)(nr >> 24),
                         0x0f, 0x05, 0xc3};
      long w = sys_native(SYS_pwrite64, memfd, stub, sizeof(stub), addr);
      if (w == (long)sizeof(stub) &&
          memcmp((void*)addr, stub, sizeof(stub)) == 0) {
        for (uintptr_t& a : done) {
          if (a == 0) { a = addr; break; }
        }
        patched++;
      } else {
        failed++;
        if (w != (long)sizeof(stub)) {
          SHIM_LOG("vdso: pwrite of %s @%#lx failed (%s); gap remains", nm,
                   (unsigned long)addr, strerror(errno));
        } else {
          SHIM_LOG("vdso: write to %s @%#lx did not take (readback "
                   "mismatch); gap remains", nm, (unsigned long)addr);
        }
      }
      break;
    }
  }
  sys_native(SYS_close, memfd);
  SHIM_LOG("vdso: neutralized %d time entry points (%d failed)", patched,
           failed);
#endif
}

#ifndef SECCOMP_SET_MODE_FILTER
#define SECCOMP_SET_MODE_FILTER 1
#endif
#ifndef SECCOMP_FILTER_FLAG_SPEC_ALLOW
#define SECCOMP_FILTER_FLAG_SPEC_ALLOW (1UL << 2)
#endif

void shim_install_seccomp() {
#if defined(__x86_64__)
  uintptr_t gate = (uintptr_t)&shim_gate_syscall;
  uint32_t gate_lo = (uint32_t)gate;
  uint32_t gate_hi = (uint32_t)(gate >> 32);
  if (gate_lo > UINT32_MAX - GATE_WINDOW) {
    SHIM_LOG("seccomp: gate straddles a 4 GiB boundary; backstop off");
    return;
  }

  struct sigaction sa;
  memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = on_sigsys;
  // SA_NODEFER: a trapped syscall inside the handler (libc internals) must
  // re-enter it — a blocked SIGSYS under seccomp kills the process
  sa.sa_flags = SA_SIGINFO | SA_NODEFER;
  if (sigaction(SIGSYS, &sa, nullptr) != 0) {
    SHIM_LOG("seccomp: sigaction failed: %s", strerror(errno));
    return;
  }
  // An inherited mask with SIGSYS blocked would turn every trap into a
  // forced kill (reference analog: shim.c:452-458 unblocks it explicitly).
  sigset_t unblock;
  sigemptyset(&unblock);
  sigaddset(&unblock, SIGSYS);
  sigprocmask(SIG_UNBLOCK, &unblock, nullptr);

  constexpr int K = (int)(sizeof(kTrapped) / sizeof(kTrapped[0]));
  // layout: [arch check][gate IP window check][ld nr]
  //         [K dispatch jeqs → TRAP / FD0 / FD01 / STDIO] [fallthrough ALLOW]
  //         FD0: ld args[0]; >= FD_BASE ? TRAP : ALLOW
  //         FD01: ld args[0]; >= FD_BASE ? TRAP : ld args[1]; ...
  //         STDIO (write/writev when log stamping): trap the emulated fd
  //           range AND fds 1-2, so stdio writes that never cross the libc
  //           PLT (glibc stdio issues the syscall internally) still reach
  //           the stamping wrapper via SIGSYS
  //         ALLOW / TRAP / KILL returns
  const int NR = 7;
  const int DISPATCH0 = 8;
  const int FD0 = DISPATCH0 + K + 1;   // after dispatch + fallthrough ALLOW
  const int FD01 = FD0 + 2;
  const int STDIO = FD01 + 4;
  const int ALLOW = STDIO + 4;
  const int TRAP = ALLOW + 1;
  const int KILL = TRAP + 1;
  struct sock_filter prog[KILL + 1];
  const uint32_t ARG0_LO = offsetof(struct seccomp_data, args);
  const uint32_t ARG1_LO = ARG0_LO + 8;
  int i = 0;
  // non-x86-64 audit arch (e.g. int 0x80 compat syscalls) would bypass
  // virtualization with wrong syscall numbering: kill loudly instead
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                       offsetof(struct seccomp_data, arch));
  prog[i++] = BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, AUDIT_ARCH_X86_64, 0,
                       (uint8_t)(KILL - 2));
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                       offsetof(struct seccomp_data, instruction_pointer) + 4);
  prog[i++] = BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K, gate_hi, 0,
                       (uint8_t)(NR - 4));
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                       offsetof(struct seccomp_data, instruction_pointer));
  prog[i++] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, gate_lo, 0,
                       (uint8_t)(NR - 6));
  prog[i++] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, gate_lo + GATE_WINDOW,
                       (uint8_t)(NR - 7), (uint8_t)(ALLOW - 7));
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS,
                       offsetof(struct seccomp_data, nr));
  for (int k = 0; k < K; k++) {
    int target = kTrapped[k].act == ACT_TRAP   ? TRAP
                 : kTrapped[k].act == ACT_FD0  ? FD0
                                               : FD01;
    if (g_log_stamp &&
        (kTrapped[k].nr == SYS_write || kTrapped[k].nr == SYS_writev))
      target = STDIO;
    prog[i] = BPF_JUMP(BPF_JMP | BPF_JEQ | BPF_K,
                       (uint32_t)kTrapped[k].nr,
                       (uint8_t)(target - (i + 1)), 0);
    i++;
  }
  prog[i++] = BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW);  // fallthrough
  // Offsets computed from explicit positions (never `i` inside a
  // `prog[i++] = ...` expression — that miscompiled to wild jumps).
  // FD0: trap iff args[0] (the fd) is in the emulated range
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS, ARG0_LO);
  prog[i] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)FD_BASE,
                     (uint8_t)(TRAP - (FD0 + 2)),
                     (uint8_t)(ALLOW - (FD0 + 2)));
  i++;
  // FD01 (dup2/dup3): trap iff either fd argument is emulated
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS, ARG0_LO);
  prog[i] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)FD_BASE,
                     (uint8_t)(TRAP - (FD01 + 2)), 0);
  i++;
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS, ARG1_LO);
  prog[i] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)FD_BASE,
                     (uint8_t)(TRAP - (FD01 + 4)),
                     (uint8_t)(ALLOW - (FD01 + 4)));
  i++;
  // STDIO: fd >= FD_BASE → TRAP; fd >= 3 → ALLOW; fd >= 1 (1 or 2) → TRAP;
  // fd 0 → ALLOW
  prog[i++] = BPF_STMT(BPF_LD | BPF_W | BPF_ABS, ARG0_LO);
  prog[i] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, (uint32_t)FD_BASE,
                     (uint8_t)(TRAP - (STDIO + 2)), 0);
  i++;
  prog[i] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, 3,
                     (uint8_t)(ALLOW - (STDIO + 3)), 0);
  i++;
  prog[i] = BPF_JUMP(BPF_JMP | BPF_JGE | BPF_K, 1,
                     (uint8_t)(TRAP - (STDIO + 4)),
                     (uint8_t)(ALLOW - (STDIO + 4)));
  i++;
  prog[i++] = BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_ALLOW);
  prog[i++] = BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_TRAP);
#ifdef SECCOMP_RET_KILL_PROCESS
  prog[i++] = BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL_PROCESS);
#else
  prog[i++] = BPF_STMT(BPF_RET | BPF_K, SECCOMP_RET_KILL);
#endif

  struct sock_fprog fprog = {(unsigned short)i, prog};
  if (prctl(PR_SET_NO_NEW_PRIVS, 1, 0, 0, 0) != 0) {
    SHIM_LOG("seccomp: no_new_privs failed: %s", strerror(errno));
    return;
  }
  // Prefer seccomp(2) with SPEC_ALLOW: plain PR_SET_SECCOMP implies
  // PR_SPEC_FORCE_DISABLE, permanently disabling speculation in every
  // managed process (reference avoids this the same way, shim.c:535-541).
  if (sys_native(SYS_seccomp, SECCOMP_SET_MODE_FILTER,
                 SECCOMP_FILTER_FLAG_SPEC_ALLOW, &fprog) != 0 &&
      prctl(PR_SET_SECCOMP, SECCOMP_MODE_FILTER, &fprog) != 0) {
    SHIM_LOG("seccomp: install failed: %s", strerror(errno));
    return;
  }
  SHIM_LOG("seccomp backstop installed (%d trapped syscalls)", K);
#endif
}

}  // namespace
